"""Launch-path flight recorder: bounded always-on ring, regime
classifier with flip cause, readback provenance through the one
tracked funnel (ops.device.readback), X-Opaque-Id propagation, the
REST surfaces, and cross-node request waterfalls that replay
byte-identically from a chaos seed.

Cluster tests ride the seeded harness of test_telemetry.py — the
recorder runs on the scheduler clock, so every t_ns / dispatch_ns in a
waterfall is a pure function of the seed.
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.telemetry import context as telectx
from elasticsearch_tpu.telemetry import flightrecorder as flightrec
from elasticsearch_tpu.telemetry.flightrecorder import (
    FlightRecorder,
    build_waterfall,
)

from test_telemetry import ChaosCluster, _setup


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------ unit: ring

def test_ring_bounded_under_overload():
    """10x capacity recorded → ring holds exactly `capacity`, totals
    keep counting (the acceptance memory bound)."""
    fr = FlightRecorder(node="n1", clock=FakeClock(), capacity=64)
    for i in range(640):
        fr.record_launch(f"k{i % 3}", "(8,128)", dispatch_ns=1_000_000,
                         cohort=4, capacity=8)
    agg = fr.aggregates()
    assert agg["ring"] == {"capacity": 64, "events": 64,
                           "recorded_total": 640}
    assert agg["launches"] == 640
    assert len(fr.events(limit=10_000)) == 64


def test_event_filters_and_paging():
    clock = FakeClock()
    fr = FlightRecorder(node="n1", clock=clock)
    for i in range(6):
        clock.t += 1.0
        fr.record_launch("plan_topk", "(8,128)", dispatch_ns=1000)
        fr.record_readback("ops.aggs.terms_counts", 4096)
    assert len(fr.events(kind="launch", limit=100)) == 6
    assert len(fr.events(kernel="plan_topk", limit=100)) == 6
    assert len(fr.events(site="ops.aggs.terms_counts", limit=100)) == 6
    assert fr.events(site="nope") == []
    late = fr.events(since_ns=int(4.5e9), limit=100)
    assert late and all(e["t_ns"] > 4.5e9 for e in late)
    # newest-first paging
    page1 = fr.events(limit=3)
    page2 = fr.events(limit=3, offset=3)
    assert [e["seq"] for e in page1] > [e["seq"] for e in page2]


def test_fill_histogram_and_percentiles():
    fr = FlightRecorder(node="n1", clock=FakeClock())
    for cohort in (1, 2, 8, 8, 8, 8):
        fr.record_launch("k", "(8,)", cohort=cohort, capacity=8)
    pct = fr.fill_percentiles()
    assert pct["p50"] == 100.0        # 4 of 6 launches were full
    assert pct["p99"] == 100.0
    agg = fr.aggregates()
    assert agg["fill_pct_overall"] == pytest.approx(
        100.0 * (1 + 2 + 8 * 4) / (8 * 6), abs=0.1)
    assert sum(agg["fill_histogram_pct"].values()) == 6


# --------------------------------------------------------- unit: regime

def test_regime_flips_with_cause_then_recovers():
    clock = FakeClock()
    fr = FlightRecorder(node="n1", clock=clock)
    assert fr.regime == "fast"
    for _ in range(6):
        clock.t += 0.05
        fr.record_launch("plan_topk", "(8,128)",
                         dispatch_ns=60_000_000)
    agg = fr.aggregates()
    assert agg["regime"]["current"] == "degraded"
    assert agg["regime"]["flips"] == 1
    assert agg["regime"]["last_flip"]["cause"] == "launch plan_topk"
    assert agg["regime"]["last_flip"]["to"] == "degraded"
    # hysteresis: 18 ms sits between exit (10) and enter (25) — stays
    # degraded instead of flapping
    for _ in range(3):
        clock.t += 0.05
        fr.record_launch("plan_topk", "(8,128)",
                         dispatch_ns=18_000_000)
    assert fr.regime == "degraded"
    for _ in range(40):
        clock.t += 0.05
        fr.record_launch("plan_topk", "(8,128)",
                         dispatch_ns=1_000_000)
    assert fr.regime == "fast"
    secs = fr.regime_seconds()
    assert secs["degraded"] > 0 and secs["fast"] > 0


def test_regime_ignores_compile_length_outliers():
    clock = FakeClock()
    fr = FlightRecorder(node="n1", clock=clock)
    for _ in range(5):
        clock.t += 1.0
        fr.record_launch("k", "(8,)", dispatch_ns=9_000_000_000)
    assert fr.regime == "fast", "compile-length launches must not flip"


def test_regime_seconds_feed_metrics_as_monotonic_counters():
    from elasticsearch_tpu.telemetry.metrics import MetricsRegistry
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    fr = FlightRecorder(node="n1", clock=clock, metrics=reg)
    for _ in range(6):
        clock.t += 0.05
        fr.record_launch("k", "(8,)", dispatch_ns=60_000_000)
    assert reg.get_value("flight.regime") == 1.0
    assert reg.get_value("flight.regime_flips") == 1
    assert reg.get_value("flight.regime_seconds.degraded") > 0
    assert reg.get_value("flight.launches") == 6


# ------------------------------------------------- unit: funnel + trace

def test_funnel_records_provenance_and_returns_host_arrays():
    fr = FlightRecorder(node="n1", clock=FakeClock())
    from elasticsearch_tpu.ops import device as device_ops
    with flightrec.activate(fr):
        one = device_ops.readback("test.site.one",
                                  np.arange(8, dtype=np.float32))
        a, b = device_ops.readback("test.site.two",
                                   np.zeros(4), np.ones(2))
    assert isinstance(one, np.ndarray) and one.shape == (8,)
    assert a.shape == (4,) and b.shape == (2,)
    agg = fr.aggregates()
    assert agg["readbacks"] == 2
    assert agg["readback_by_site"]["test.site.one"]["count"] == 1
    assert agg["readback_by_site"]["test.site.one"]["bytes"] == 32
    assert agg["readback_by_site"]["test.site.two"]["bytes"] == \
        4 * 8 + 2 * 8


def test_events_carry_ambient_trace_and_span():
    from elasticsearch_tpu.telemetry.tracing import Tracer
    tracer = Tracer(node="n1", clock=FakeClock())
    fr = FlightRecorder(node="n1", clock=FakeClock())
    span = tracer.start_span("search")
    with telectx.activate_span(span):
        fr.record_launch("k", "(8,)", dispatch_ns=1000)
        fr.record_readback("s", 16)
    span.finish()
    evs = fr.events(limit=10)
    assert all(e["trace_id"] == span.trace_id for e in evs)
    assert all(e["span_id"] == span.span_id for e in evs)
    summary = fr.summary_for_trace(span.trace_id)
    assert summary["launches"] == 1 and summary["readbacks"] == 1


def test_context_bind_carries_recorder_and_opaque_across_tasks():
    """telemetry/context.capture()/bind() must move the ambient
    recorder AND the X-Opaque-Id across scheduler task boundaries —
    the cross-thread half of every cluster test below."""
    fr = FlightRecorder(node="n1", clock=FakeClock())
    with flightrec.activate(fr), telectx.activate_opaque("req-42"):
        bound = telectx.bind(
            lambda: (flightrec.current(), telectx.current_opaque_id()))
    assert flightrec.current() is None
    assert telectx.current_opaque_id() is None
    got_fr, got_opaque = bound()     # runs "on the other task"
    assert got_fr is fr
    assert got_opaque == "req-42"
    assert flightrec.current() is None


def test_task_captures_opaque_id_into_headers():
    from elasticsearch_tpu.transport.tasks import Task
    with telectx.activate_opaque("admin-7"):
        t = Task(1, "transport", "indices:data/read/search")
    d = t.to_dict("n1")
    assert d["headers"] == {"X-Opaque-Id": "admin-7"}
    assert "headers" not in Task(2, "transport", "x").to_dict("n1")


# ------------------------------------------------ unit: waterfall stitch

def test_build_waterfall_attaches_events_and_merges_nodes():
    spans = [
        {"span_id": "c/1", "parent_id": None, "name": "search",
         "start_ms": 0.0, "duration_ms": 10.0},
        {"span_id": "d/2", "parent_id": "c/1", "name": "shard[i][0]",
         "start_ms": 1.0, "duration_ms": 6.0},
    ]
    events = [{"kind": "launch", "seq": 1, "node": "dn-1", "t_ns": 2,
               "kernel": "k", "span_id": "d/2", "trace_id": "t1"},
              {"kind": "readback", "seq": 2, "node": "dn-1", "t_ns": 3,
               "site": "s", "span_id": "gone", "trace_id": "t1"}]
    w = build_waterfall("t1", [
        {"node": "coord", "spans": [spans[0]], "events": []},
        {"node": "dn-1", "spans": [spans[1]], "events": events},
    ])
    assert w["nodes"] == ["coord", "dn-1"]
    assert w["span_count"] == 2 and w["event_count"] == 2
    root = w["waterfall"][0]
    assert root["name"] == "search" and root["events"] == []
    child = root["children"][0]
    assert child["name"] == "shard[i][0]"
    assert [e["kind"] for e in child["events"]] == ["launch"]
    # the event whose span aged out stays visible, not silently dropped
    assert [e["seq"] for e in w["unattached_events"]] == [2]
    # self time: parent paid 10 - 6 = 4ms on top of its child
    assert root["self_ns"] == 4_000_000
    assert build_waterfall("t2", [{"node": "x", "spans": [],
                                   "events": []}]) is None


# ------------------------------------------------------- REST, one node

@pytest.fixture(scope="module")
def rest_node(tmp_path_factory):
    from elasticsearch_tpu.node import Node
    node = Node(data_path=str(tmp_path_factory.mktemp("flight_node")))
    c = node.rest_controller
    c.dispatch("PUT", "/idx", {}, {
        "settings": {
            "index.search.slowlog.threshold.query.warn": "0ms"},
        "mappings": {"properties": {"cat": {"type": "keyword"}}}})
    for i in range(30):
        c.dispatch("PUT", f"/idx/_doc/{i}", {},
                   {"title": f"fox doc {i}", "cat": f"c{i % 3}",
                    "rank": i})
    c.dispatch("POST", "/idx/_refresh", {}, None)
    yield node
    node.close()


SEARCH_BODY = {"query": {"match": {"title": "fox"}}, "size": 5,
               "aggs": {"cats": {"terms": {"field": "cat"}}}}


def _search(node, body, headers=None):
    status, r = node.rest_controller.dispatch(
        "POST", "/idx/_search", {}, body, headers=headers)
    assert status == 200, r
    return r


def test_flight_recorder_endpoint_records_serving_path(rest_node):
    """ACCEPTANCE: the product serving path (REST search with a terms
    agg) leaves launch events AND site-attributed readbacks in the
    ring; `GET /_flight_recorder` filters by kind/site."""
    r = _search(rest_node, SEARCH_BODY)
    assert r["aggregations"]["cats"]["buckets"]
    d = rest_node.rest_controller.dispatch
    st, out = d("GET", "/_flight_recorder", {}, None)
    assert st == 200
    kinds = {e["kind"] for e in out["events"]}
    assert kinds >= {"launch", "readback"}
    agg = out["aggregates"]
    assert agg["launches"] > 0 and agg["readbacks"] > 0
    # every readback names its funnel call site (dotted provenance
    # label); which site serves depends on corpus-scale lane choice
    sites = agg["readback_by_site"]
    assert sites and all("." in s for s in sites)
    assert sum(v["bytes"] for v in sites.values()) > 0
    # filters narrow server-side
    site = next(iter(sites))
    st, only_rb = d("GET", "/_flight_recorder",
                    {"kind": "readback", "site": site}, None)
    assert only_rb["events"]
    assert all(e["site"] == site for e in only_rb["events"])


def test_nodes_stats_shows_nonzero_readback_by_site(rest_node):
    """ACCEPTANCE: `_nodes/stats` readback-by-site is nonzero for the
    product serving path."""
    _search(rest_node, SEARCH_BODY)
    st, stats = rest_node.rest_controller.dispatch(
        "GET", "/_nodes/stats", {}, None)
    assert st == 200
    fl = next(iter(stats["nodes"].values()))["telemetry"][
        "flight_recorder"]
    assert fl["readbacks"] > 0
    assert sum(s["bytes"] for s in fl["readback_by_site"].values()) > 0
    assert fl["regime"]["current"] in ("fast", "degraded")


def test_opaque_id_header_reaches_slowlog_with_flight_fields(rest_node):
    """X-Opaque-Id flows REST header → ambient context → slowlog; the
    entry also carries the launch-path summary of ITS trace."""
    r = _search(rest_node, SEARCH_BODY,
                headers={"x-opaque-id": "tenant-blue"})
    entry = rest_node.search_service.slowlog_recent[-1]
    assert entry["x_opaque_id"] == "tenant-blue"
    assert entry["trace.id"] == r["_headers"]["trace.id"]
    assert entry["readbacks"] >= 1
    assert entry["regime"] in ("fast", "degraded")
    assert entry["cohort_fill_pct"] is None \
        or 0.0 <= entry["cohort_fill_pct"] <= 100.0
    # no header → no x_opaque_id key (field is opt-in, not null noise)
    _search(rest_node, SEARCH_BODY)
    assert "x_opaque_id" not in \
        rest_node.search_service.slowlog_recent[-1]


def test_single_node_waterfall_endpoint(rest_node):
    r = _search(rest_node, SEARCH_BODY)
    tid = r["_headers"]["trace.id"]
    st, w = rest_node.rest_controller.dispatch(
        "GET", f"/_flight_recorder/waterfall/{tid}", {}, None)
    assert st == 200
    assert w["trace_id"] == tid and w["span_count"] > 0
    names = set()

    def walk(n):
        names.add(n["name"])
        for c in n["children"]:
            walk(c)
    for root in w["waterfall"]:
        walk(root)
    assert "rest.search" in names
    assert any(n.startswith("shard[idx]") for n in names)
    st, _ = rest_node.rest_controller.dispatch(
        "GET", "/_flight_recorder/waterfall/no-such-trace", {}, None)
    assert st == 404


# ------------------------------------------------------- 3-node cluster

SORTED_BODY = {"query": {"match": {"body": "fox"}},
               "sort": [{"n": "desc"}], "size": 5}


def _latest_search_trace(coord):
    return next(t["trace_id"]
                for t in coord.telemetry.tracer.recent_traces()
                if t["root"] == "search")


@pytest.mark.chaos(seed=171)
def test_cross_node_waterfall_covers_all_three_nodes(
        tmp_path, chaos_seed):
    """ACCEPTANCE: the stitched waterfall of a 2-shard/1-replica search
    on a 3-node cluster spans coordinator + both data nodes, with
    launch/readback events attached to the shard spans that issued
    them."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster)
    coord = cluster.master()
    cluster.call(coord.search, "logs", SORTED_BODY)
    tid = _latest_search_trace(coord)
    w = cluster.call(coord.flight_waterfall, tid)
    assert w is not None and w["trace_id"] == tid
    # every node that held a span or event of this trace is named;
    # 2 shards × (primary, replica) over 3 nodes always touches ≥ 2
    assert len(w["nodes"]) >= 2, f"seed={chaos_seed}: {w['nodes']}"
    shard_events = []

    def walk(n):
        # device events land on the data-node handler span
        # (shard_query), a child of the coordinator's shard[...]
        # attempt span — both are "shard spans" of this trace
        if n["name"].startswith("shard"):
            shard_events.extend(n["events"])
        for c in n["children"]:
            walk(c)
    for root in w["waterfall"]:
        walk(root)
    assert shard_events, f"seed={chaos_seed}: no events on shard spans"
    assert {e["kind"] for e in shard_events} >= {"launch", "readback"}
    # provenance: events name the data node that recorded them, and it
    # differs across shards when shards landed on different nodes
    ev_nodes = {e["node"] for e in shard_events}
    assert ev_nodes <= {c.local_node.name
                        for c in cluster.cluster_nodes.values()}
    assert w["event_count"] >= len(shard_events)


@pytest.mark.chaos(seed=171)
def test_failover_attempts_are_children_of_the_same_trace(
        tmp_path, chaos_seed):
    """Seeded chaos: an injected shard failure retries on another copy
    — BOTH attempts appear in the one waterfall as children of the same
    trace, and the succeeding attempt carries the device events."""
    from elasticsearch_tpu.cluster.search_action import (
        QUERY_PHASE_ACTION,
    )
    from elasticsearch_tpu.testing.faults import ERROR, FaultRule
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster)
    coord = cluster.coordinator_excluding("dn-0")
    cluster.injector.add_rule(FaultRule(
        action=QUERY_PHASE_ACTION, node="dn-0", mode=ERROR))
    resp = cluster.call(coord.search, "logs", SORTED_BODY)
    assert resp["_shards"]["failed"] == 0, f"seed={chaos_seed}"
    tid = _latest_search_trace(coord)
    w = cluster.call(coord.flight_waterfall, tid)
    attempts, handlers = [], []

    def walk(n):
        if n["name"].startswith("shard[logs]"):
            attempts.append(n)
        elif n["name"] == "shard_query":
            handlers.append(n)
        for c in n["children"]:
            walk(c)
    for root in w["waterfall"]:
        walk(root)
    # BOTH attempts of the failed-over shard are children of the ONE
    # trace's waterfall: the failed copy on dn-0 and its retry
    failed = [a for a in attempts if a["tags"]["outcome"] == "failed"]
    ok = [a for a in attempts if a["tags"]["outcome"] == "ok"]
    assert failed and ok, f"seed={chaos_seed}: {attempts}"
    assert failed[0]["tags"]["node"] == "dn-0"
    retried = [a for a in ok
               if a["name"] == failed[0]["name"]]
    assert retried and retried[0]["tags"]["node"] != "dn-0", \
        f"seed={chaos_seed}"
    # device events live on the data-node shard_query handler spans of
    # the same waterfall — and NONE on the faulted node, whose handler
    # never ran
    ev = [e for h in handlers for e in h["events"]]
    assert ev, f"seed={chaos_seed}: no device events on shard_query"
    assert "dn0" not in {e["node"] for e in ev}, f"seed={chaos_seed}"


@pytest.mark.chaos(seed=171)
def test_same_seed_byte_identical_waterfall(tmp_path, chaos_seed):
    """ACCEPTANCE: two fresh runs of the same chaos seed produce
    byte-identical waterfalls — every t_ns, dispatch_ns, span time and
    stitch order reads the deterministic scheduler clock."""
    from elasticsearch_tpu.cluster.search_action import (
        QUERY_PHASE_ACTION,
    )
    from elasticsearch_tpu.testing.faults import ERROR, FaultRule

    def one_run(tag):
        cluster = ChaosCluster(3, tmp_path / tag, seed=chaos_seed)
        _setup(cluster)
        coord = cluster.coordinator_excluding("dn-0")
        cluster.injector.add_rule(FaultRule(
            action=QUERY_PHASE_ACTION, node="dn-0", mode=ERROR))
        cluster.call(coord.search, "logs", SORTED_BODY)
        tid = _latest_search_trace(coord)
        return cluster.call(coord.flight_waterfall, tid)

    one_run("warm")      # warm the process-global jit caches
    w_a = one_run("a")
    w_b = one_run("b")
    assert json.dumps(w_a, sort_keys=True) == \
        json.dumps(w_b, sort_keys=True), \
        f"seed={chaos_seed}: waterfalls diverged on replay"
    assert w_a["event_count"] > 0


# --------------------------------------------------------------- health

def test_health_indicator_flags_stuck_degraded_regime():
    from elasticsearch_tpu.health.indicators import (
        FlightRegimeIndicator,
    )
    from elasticsearch_tpu.health.indicator import HealthContext
    from elasticsearch_tpu.telemetry.history import MetricsHistory
    from elasticsearch_tpu.telemetry.metrics import MetricsRegistry
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    fr = FlightRecorder(node="n1", clock=clock, metrics=reg)
    hist = MetricsHistory(reg, clock, interval=1.0)
    hist.advance()
    for _ in range(20):
        clock.t += 3.0
        fr.record_launch("plan_topk", "(8,)", dispatch_ns=60_000_000)
        hist.advance()
    ctx = HealthContext(flight=fr, history=hist,
                        metrics=reg, now=clock)
    res = FlightRegimeIndicator().compute(ctx)
    assert res.status == "red", res
    diag = next(d for d in res.diagnoses
                if d.id == "device_regime:degraded")
    assert "plan_topk" in diag.cause
    assert "_flight_recorder" in diag.action


def test_health_indicator_flags_underfilled_batcher():
    from elasticsearch_tpu.health.indicators import (
        FlightRegimeIndicator,
    )
    from elasticsearch_tpu.health.indicator import HealthContext
    from elasticsearch_tpu.telemetry.history import MetricsHistory
    from elasticsearch_tpu.telemetry.metrics import MetricsRegistry
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    fr = FlightRecorder(node="n1", clock=clock, metrics=reg)
    hist = MetricsHistory(reg, clock, interval=1.0)
    hist.advance()
    for _ in range(40):
        clock.t += 1.0
        fr.record_launch("k", "(8,)", dispatch_ns=1_000_000,
                         cohort=1, capacity=8)   # 12.5% fill
        hist.advance()
    ctx = HealthContext(flight=fr, history=hist,
                        metrics=reg, now=clock)
    res = FlightRegimeIndicator().compute(ctx)
    assert res.status == "yellow", res
    assert any(d.id == "device_regime:underfilled_batcher"
               for d in res.diagnoses)
