"""Macro-workload observability (bench/macro.py + the workload-class
rail): request-shape classification, class attribution across the REST
and cluster surfaces (scroll continuations, async status docs), the
noisy-hog isolation pin (a hog tenant's burst burns ITS class budget
while the interactive class holds, and workload_slo + noisy_neighbor
each name the right culprit), same-seed byte-identical macro replay,
and the ``bench.py --macro-smoke`` tier-1 entry.

The chaos paths replay byte-identically from their queue seed."""

import json
import os
import subprocess
import sys
import time

import pytest

from test_cluster_node import SimDataCluster, _index_some_docs

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.telemetry import context as telectx
from elasticsearch_tpu.telemetry.workload import (
    CLASS_AGGS,
    CLASS_INTERACTIVE,
    CLASS_SCROLL,
    DEFAULT_CLASS,
    classify_search_request,
)

# ---------------------------------------------------------------------------
# boundary classification + context rail
# ---------------------------------------------------------------------------


def test_classify_search_request_shapes():
    assert classify_search_request(
        {"query": {"match": {"b": "x"}}}) == CLASS_INTERACTIVE
    assert classify_search_request(
        {"query": {"bool": {"must": []}}}) == CLASS_INTERACTIVE
    assert classify_search_request(
        {"knn": {"field": "v", "query_vector": [1.0]}}) \
        == CLASS_INTERACTIVE
    assert classify_search_request(
        {"aggs": {"a": {"terms": {"field": "c"}}}}) == CLASS_AGGS
    assert classify_search_request(
        {"aggregations": {"a": {"avg": {"field": "p"}}}}) == CLASS_AGGS
    assert classify_search_request({}, scroll=60.0) == CLASS_SCROLL
    assert classify_search_request(
        {"pit": {"id": "x"}}) == CLASS_SCROLL
    assert classify_search_request(None) == CLASS_INTERACTIVE


def test_workload_class_rides_capture_bind():
    with telectx.activate_workload_class("bulk"):
        bound = telectx.bind(lambda: telectx.current_workload_class())
    with telectx.activate_workload_class("aggs"):
        assert bound() == "bulk"
        assert telectx.current_workload_class() == "aggs"
    assert telectx.current_workload_class() is None


def test_workload_header_round_trips():
    with telectx.activate_workload_class("scroll"):
        headers = telectx.stamp_task_headers({})
    assert headers[telectx.WORKLOAD_HEADER] == "scroll"
    with telectx.incoming(headers):
        assert telectx.current_workload_class() == "scroll"
    assert telectx.current_workload_class() is None


# ---------------------------------------------------------------------------
# single-process REST surface
# ---------------------------------------------------------------------------


@pytest.fixture
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, headers=None,
       expect=200):
    status, resp = node.rest_controller.dispatch(
        method, path, params, body, headers=headers)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def _seed(node, index="logs", settings=None):
    do(node, "PUT", f"/{index}", body={"settings": settings or {}})
    do(node, "PUT", f"/{index}/_doc/1",
       body={"body": "quick brown fox", "category": "a"}, expect=201)
    do(node, "POST", f"/{index}/_refresh")


def test_request_shapes_classify_into_workload_stats(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match": {"body": "fox"}}})
    do(node, "POST", "/logs/_search",
       body={"size": 0,
             "aggs": {"c": {"terms": {"field": "category"}}}})
    stats = do(node, "GET", "/_workload/stats")
    assert stats["nodes"] == [node.node_id]
    assert stats["classes"]["interactive"]["search"]["count"] == 1
    assert stats["classes"]["aggs"]["search"]["count"] == 1
    # the REST bulk handler charges the ingest to the bulk class
    ndjson = "\n".join(json.dumps(line) for line in [
        {"index": {"_index": "logs", "_id": "b1"}},
        {"body": "more fox"},
    ])
    do(node, "POST", "/_bulk", params={"refresh": "true"}, body=ndjson)
    stats = do(node, "GET", "/_workload/stats")
    assert stats["classes"]["bulk"]["indexing"]["bytes"] > 0


def test_workload_header_beats_classification(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match": {"body": "fox"}}},
       headers={"X-Workload-Class": "canary"})
    classes = do(node, "GET", "/_workload/stats")["classes"]
    assert classes["canary"]["search"]["count"] == 1
    assert classes.get("interactive", {}).get(
        "search", {}).get("count", 0) == 0


def test_cat_workload_shares_stats_shaping(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match": {"body": "fox"}}})
    stats = do(node, "GET", "/_workload/stats")
    cat = do(node, "GET", "/_cat/workload")["_cat"]
    lines = cat.splitlines()
    assert lines[0].startswith("class")
    for c, e in stats["classes"].items():
        row = next(ln for ln in lines[1:] if ln.split()[0] == c)
        assert row.split()[1] == str(e["search"]["count"])


def test_slowlog_and_profile_carry_class(node):
    _seed(node, index="slowidx", settings={
        "index.search.slowlog.threshold.query.warn": "0ms"})
    do(node, "POST", "/slowidx/_search",
       body={"query": {"match": {"body": "fox"}}})
    entries = [e for e in node.search_service.slowlog_recent
               if e.get("search.class") == "interactive"]
    assert entries, list(node.search_service.slowlog_recent)


# ---------------------------------------------------------------------------
# cluster attribution: cursor continuations, async status docs
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=19)
def test_scroll_continuations_stay_in_scroll_class(tmp_path,
                                                   chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    c.call(m.create_index, "logs", number_of_shards=2,
           number_of_replicas=1)
    c.run_for(60)
    _index_some_docs(c, m, n=20)
    page = c.call(m.search, "logs",
                  {"query": {"match_all": {}}, "size": 6}, scroll=60.0)
    pages = 1
    while page["hits"]["hits"]:
        page = c.call(m.scroll, page["_scroll_id"], 60.0)
        pages += 1
    merged = c.call(m.workload_stats)
    # the open AND every continuation landed in the scroll class —
    # nothing leaked into interactive or _default
    assert merged["classes"]["scroll"]["search"]["count"] == pages
    assert merged["classes"].get("interactive", {}).get(
        "search", {}).get("count", 0) == 0


@pytest.mark.chaos(seed=29)
def test_async_status_doc_carries_class_and_tenant(tmp_path,
                                                   chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    c.call(m.create_index, "logs", number_of_shards=2,
           number_of_replicas=1)
    c.run_for(60)
    _index_some_docs(c, m, n=8)
    with telectx.activate_tenant("t9"):
        sub = c.call(m.submit_async_search, "logs",
                     {"query": {"match_all": {}}, "size": 2})
    assert sub["tenant"] == "t9"
    assert sub["search.class"] == "async"
    got = c.call(m.get_async_search, sub["id"])
    assert got["tenant"] == "t9"
    assert got["search.class"] == "async"
    merged = c.call(m.workload_stats)
    assert merged["classes"]["async"]["search"]["count"] == 1


# ---------------------------------------------------------------------------
# the isolation pin: a hog's burst burns ITS class budget while the
# interactive class holds, and each indicator names its culprit
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=43)
def test_hog_burst_burns_own_class_interactive_holds(tmp_path,
                                                     chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    for cn in c.cluster_nodes.values():
        # interactive is effectively un-burnable; the hog's drain
        # class is held to an impossible bound so ITS budget burns
        cn.telemetry.workload.slo_objectives.update(
            {"interactive": 60_000.0, "scroll": 0.001})
        cn.telemetry.tenants.slo_objectives = {
            "quiet": 60_000.0, "hog": 60_000.0}
    c.call(m.create_index, "quietidx", number_of_shards=2,
           number_of_replicas=1,
           settings={"index.tenant.default": "quiet"})
    c.call(m.create_index, "hogidx", number_of_shards=2,
           number_of_replicas=1,
           settings={"index.tenant.default": "hog"})
    c.run_for(60)
    _index_some_docs(c, m, index="quietidx", n=10)
    _index_some_docs(c, m, index="hogidx", n=30)
    baseline = c.call(m.health_report)  # ring anchor sample
    assert baseline["indicators"]["workload_slo"]["status"] == "green"

    # quiet tenant's interactive traffic INSIDE the window
    for _ in range(9):
        c.call(m.search, "quietidx",
               {"tenant": "quiet",
                "query": {"match": {"body": "fox"}}, "size": 3})
    # hog tenant's scroll drains: every page violates the pinned
    # scroll objective (class budget burns), twice over for the floor
    for _ in range(2):
        page = c.call(m.search, "hogidx",
                      {"tenant": "hog", "query": {"match_all": {}},
                       "size": 5}, scroll=60.0)
        while page["hits"]["hits"]:
            page = c.call(m.scroll, page["_scroll_id"], 60.0)
    # hog tenant's rejection burst: shrink the coordinator's pressure
    # budget so its bulks shed — the noisy_neighbor dimension
    saved = m.indexing_pressure.limit
    m.indexing_pressure.limit = 64
    rejected = 0
    for i in range(8):
        try:
            c.call(m.bulk, "hogidx",
                   [{"op": "index", "id": f"burst-{i}",
                     "source": {"body": "x" * 300}}])
        except Exception:
            rejected += 1
    m.indexing_pressure.limit = saved
    assert rejected == 8
    c.run_for(11)  # cross the next history-ring boundary

    report = c.call(m.health_report)
    slo = report["indicators"]["workload_slo"]
    assert slo["status"] in ("yellow", "red"), f"seed={chaos_seed}"
    named = {r for d in slo["diagnosis"]
             for r in d["affected_resources"]}
    assert named == {"scroll"}, f"seed={chaos_seed}: {named}"
    noisy = report["indicators"]["noisy_neighbor"]
    assert noisy["status"] in ("yellow", "red"), f"seed={chaos_seed}"
    assert {r for d in noisy["diagnosis"]
            for r in d["affected_resources"]} == {"hog"}

    merged = c.call(m.workload_stats)
    inter = merged["classes"]["interactive"]
    scroll = merged["classes"]["scroll"]
    # the hog degraded ITS class; the interactive class held
    assert scroll["slo"]["violations"] > 0
    assert scroll["slo"]["budget_burn_pct"] > 0.0
    assert inter["slo"]["violations"] == 0
    assert inter["slo"]["budget_burn_pct"] == 0.0
    assert inter["search"]["failed"] == 0
    # the bulk shed charged the bulk class, not the search classes
    assert merged["classes"]["bulk"]["indexing"]["rejections"] == 8
    assert inter["indexing"]["rejections"] == 0


# ---------------------------------------------------------------------------
# the macro harness: replay stability + the tier-1 smoke entry
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=7)
def test_macro_transcript_replays_byte_identical(tmp_path, chaos_seed):
    """Two same-seed smoke runs — each surviving an injected reroute
    AND a node bounce — render the same bytes end to end, transcript
    included."""
    from elasticsearch_tpu.bench.macro import run_macro

    r1 = run_macro(seed=chaos_seed, smoke=True,
                   root=str(tmp_path / "a"))
    r2 = run_macro(seed=chaos_seed, smoke=True,
                   root=str(tmp_path / "b"))
    assert json.dumps(r1, sort_keys=True) == \
        json.dumps(r2, sort_keys=True), f"seed={chaos_seed}"
    # the survival contract: every acked write re-counted after the
    # disruptions, zero loss, every in-flight request drained
    assert r1["acked_write_loss"] == 0, f"seed={chaos_seed}"
    assert r1["acked_writes"] > 0 and r1["drained"]
    assert [d["event"] for d in r1["disruptions"]] == \
        ["reroute", "node_stop", "node_restart"]
    assert r1["disruptions"][0]["acked"], f"seed={chaos_seed}"
    # the run the summary reports is the run the rail observed: the
    # mid-chaos probe caught the burning class by name
    assert r1["workload_slo_mid"]["status"] in ("yellow", "red")
    assert r1["workload_slo_mid"]["named"] == ["interactive"]
    for cls in ("interactive", "bulk", "aggs", "scroll", "async"):
        assert r1["classes"][cls]["ops"] > 0, cls
    assert r1["classes"]["bulk"]["indexing_bytes"] > 0
    assert r1["transcript_rows"] == len(r1["transcript"])


def test_macro_smoke_subprocess_banks_rider_rows():
    """``bench.py --macro-smoke`` is the tier-1 entry: one smoke run,
    rows banked as a parseable JSON line, inside the 30s budget."""
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "bench.py"),
         "--macro-smoke", "7"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    host_s = time.time() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "macro" in payload, payload.get("skipped")
    m = payload["macro"]
    assert m["acked_write_loss"] == 0
    assert m["drained"] is True
    assert [d["event"] for d in m["disruptions"]] == \
        ["reroute", "node_stop", "node_restart"]
    assert "transcript" not in m          # folded to the sha256
    assert len(m["transcript_sha256"]) == 64
    assert set(m["classes"]) == \
        {"interactive", "bulk", "aggs", "scroll", "async"}
    assert host_s <= 30.0, f"smoke budget blown: {host_s:.1f}s"


def test_untracked_setup_work_lands_in_default_class(tmp_path):
    """The harness's own setup/verification traffic runs under the
    reserved ``_default`` class, so the measured per-class tables hold
    ONLY the scheduled mix."""
    c = SimDataCluster(3, tmp_path, seed=11)
    m = c.stabilise()
    c.call(m.create_index, "plain", number_of_shards=1,
           number_of_replicas=0)
    c.run_for(30)
    with telectx.activate_workload_class("_default"):
        _index_some_docs(c, m, index="plain", n=4)
        c.call(m.search, "plain",
               {"query": {"match_all": {}}, "size": 1})
    merged = c.call(m.workload_stats)
    assert merged["classes"][DEFAULT_CLASS]["search"]["count"] == 1
    assert merged["classes"].get("interactive", {}).get(
        "search", {}).get("count", 0) == 0
