"""Cluster health & diagnostics (health/ + telemetry/history.py):
indicator catalog verdicts, the metrics time-series ring that turns
monotonic counters into storm-shaped rates, the stalled-progress
watchdog, and the `cluster:monitor/health_report[n]` fan-out surface
(ref strategy: the reference's HealthServiceTests /
ShardsAvailabilityHealthIndicatorServiceTests crossed with the
deterministic chaos simulation of AbstractCoordinatorTestCase).

The chaos paths replay byte-identically from their queue seed."""

import json

import pytest

from test_cluster_node import SimDataCluster, _index_some_docs

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.health import (
    DEFAULT_INDICATORS,
    HealthContext,
    HealthStatus,
    StalledProgressWatchdog,
    merge_node_reports,
    shard_availability_summary,
)
from elasticsearch_tpu.health.indicators import (
    DeviceEngineIndicator,
    IndexingPressureIndicator,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.telemetry.history import MetricsHistory
from elasticsearch_tpu.telemetry.metrics import MetricsRegistry, _label_key
from elasticsearch_tpu.testing.deterministic import BLACKHOLE, DISCONNECTED
from elasticsearch_tpu.utils.breaker import (
    CircuitBreaker,
    CircuitBreakingException,
)

INDICATOR_NAMES = [cls.name for cls in DEFAULT_INDICATORS]


class _Clock:
    """Manually-advanced clock seam for the unit-level tests."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _ring(interval=10.0, retention=600.0, t0=1000.0):
    clock = _Clock(t0)
    reg = MetricsRegistry(clock=clock)
    hist = MetricsHistory(reg, clock, interval=interval,
                          retention=retention)
    return clock, reg, hist


# ---------------------------------------------------------------------------
# single-process REST surface
# ---------------------------------------------------------------------------


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def test_health_report_green_catalog(node):
    r = do(node, "GET", "/_health_report")
    assert r["status"] == "green"
    assert sorted(r["indicators"]) == sorted(INDICATOR_NAMES)
    for name, ind in r["indicators"].items():
        assert ind["status"] == "green", (name, ind)
        assert ind["symptom"]
        # fan-out shape even single-process: details nest per node
        assert node.node_id in ind["details"]["nodes"]
        # green verdicts carry no impacts/diagnosis noise
        assert "impacts" not in ind and "diagnosis" not in ind


def test_health_report_single_indicator_filter(node):
    r = do(node, "GET", "/_health_report/circuit_breakers")
    assert list(r["indicators"]) == ["circuit_breakers"]
    assert r["status"] == r["indicators"]["circuit_breakers"]["status"]


def test_health_report_unknown_indicator_400(node):
    r = do(node, "GET", "/_health_report/no_such_thing", expect=400)
    assert r["error"]["type"] == "illegal_argument_exception"
    assert "no_such_thing" in r["error"]["reason"]


def test_cluster_health_and_cat_health_share_status(node):
    do(node, "PUT", "/books", body={
        "settings": {"index": {"number_of_shards": 3}}})
    h = do(node, "GET", "/_cluster/health")
    assert h["status"] == "green"
    assert h["active_primary_shards"] == 3
    assert h["active_shards_percent_as_number"] == 100.0
    cat = do(node, "GET", "/_cat/health")["_cat"]
    # _cat/health is a projection of _cluster/health, same status token
    assert f" {h['status']} " in cat
    # ...and the shards_availability indicator agrees (one impl)
    r = do(node, "GET", "/_health_report/shards_availability")
    assert r["indicators"]["shards_availability"]["status"] == h["status"]


def test_nodes_stats_history_param(node):
    plain = do(node, "GET", "/_nodes/stats", params={})
    tele = plain["nodes"][node.node_id]["telemetry"]
    assert "history" not in tele
    withh = do(node, "GET", "/_nodes/stats", params={"history": "true"})
    hist = withh["nodes"][node.node_id]["telemetry"]["history"]
    assert hist["interval_s"] > 0 and hist["capacity"] >= 2
    assert hist["samples"] >= 1          # the read path advance()d
    assert hist["memory_bytes"] > 0


# ---------------------------------------------------------------------------
# metrics history ring: rates vs point-in-time counters
# ---------------------------------------------------------------------------


def test_history_samples_on_interval_boundaries_only():
    clock, reg, hist = _ring(interval=10.0, t0=1000.0)
    reg.inc("x")
    assert hist.advance() is True       # first boundary: 1000.0
    assert hist.advance() is False      # same boundary: no-op
    clock.advance(9.9)
    assert hist.advance() is False      # 1009.9 // 10 == same boundary
    clock.advance(0.2)
    assert hist.advance() is True       # crossed 1010.0
    assert [ts for ts, _ in hist.samples()] == [1000.0, 1010.0]


def test_history_ring_bounded():
    clock, reg, hist = _ring(interval=1.0, retention=5.0)
    assert hist.capacity == 6
    for _ in range(20):
        clock.advance(1.0)
        hist.advance()
    assert len(hist.samples()) == 6
    assert hist.memory_bytes() > 0


def test_history_snapshots_are_scalar_only():
    clock, reg, hist = _ring()
    reg.inc("hits", 3)
    reg.observe("lat_ms", 5.0)
    reg.observe("lat_ms", 7.0)
    hist.advance()
    _, snap = hist.samples()[-1]
    # histograms contribute .count/.sum scalars — never bucket arrays
    assert snap[("lat_ms.count", _label_key({}))] == 2.0
    assert snap[("lat_ms.sum", _label_key({}))] == 12.0
    assert not any("bucket" in name for name, _ in snap)
    assert all(isinstance(v, float) for v in snap.values())


def test_history_delta_and_rate_anchor_at_newest_sample():
    clock, reg, hist = _ring(interval=10.0)
    reg.inc("req", 100)
    hist.advance()                      # t=1000: 100
    clock.advance(10)
    reg.inc("req", 30)
    hist.advance()                      # t=1010: 130
    clock.advance(10)
    reg.inc("req", 5)
    hist.advance()                      # t=1020: 135
    assert hist.delta("req", 60.0) == 35.0
    assert hist.rate("req", 60.0) == pytest.approx(35.0 / 20.0)
    # narrow window: only the last hop
    assert hist.delta("req", 10.0) == 5.0
    # live counter churn WITHOUT a new sample changes nothing: queries
    # read the ring only (replay determinism)
    reg.inc("req", 1000)
    assert hist.delta("req", 60.0) == 35.0


def test_history_rate_distinguishes_storm_from_boot_accumulation():
    """The acceptance case: a point-in-time counter cannot tell '300
    compiles ever' from '300 compiles this minute' — the ring can."""
    clock, reg, hist = _ring(interval=10.0)
    # an old node that compiled 300 kernels at boot
    reg.inc("engine.compile.count", 300)
    hist.advance()
    clock.advance(10)
    hist.advance()
    ctx = HealthContext(history=hist)
    res = DeviceEngineIndicator().safe_compute(ctx)
    assert res.status == HealthStatus.GREEN
    assert res.details["compiles_per_min"] == 0.0
    assert reg.get_value("engine.compile.count") == 300  # the decoy
    # now a real storm: 35 fresh compiles inside one sample interval
    reg.inc("engine.compile.count", 35)
    clock.advance(10)
    hist.advance()
    res = DeviceEngineIndicator().safe_compute(ctx)
    assert res.status in (HealthStatus.YELLOW, HealthStatus.RED)
    assert res.details["compiles_per_min"] >= 30.0
    assert res.diagnoses[0].id == "device_engine:compile_storm"


class _StubPressure:
    def __init__(self, current=0, limit=10 ** 9, lifetime_rejections=112):
        self.current = current
        self.limit = limit
        self.lifetime = lifetime_rejections

    def stats(self):
        return {"limit_in_bytes": self.limit,
                "memory": {
                    "current": {"coordinating_in_bytes": self.current},
                    "total": {"coordinating_rejections": self.lifetime}}}


def test_history_rejection_burst_vs_lifetime_count():
    clock, reg, hist = _ring(interval=10.0)
    # 100 rejections accumulated long ago (before the ring existed)
    reg.inc("indexing_pressure.rejections", 100, stage="coordinating")
    hist.advance()
    clock.advance(10)
    hist.advance()
    ctx = HealthContext(history=hist, indexing_pressure=_StubPressure())
    res = IndexingPressureIndicator().safe_compute(ctx)
    assert res.status == HealthStatus.GREEN, res.symptom
    assert res.details["lifetime_rejections"] == 112   # decoy is visible
    # a real burst: 12 rejections inside the trailing window, spread
    # across stages (delta_total sums label series)
    reg.inc("indexing_pressure.rejections", 7, stage="coordinating")
    reg.inc("indexing_pressure.rejections", 5, stage="primary")
    clock.advance(10)
    hist.advance()
    res = IndexingPressureIndicator().safe_compute(ctx)
    assert res.status == HealthStatus.RED
    assert res.details["recent_rejections"] == 12.0
    assert res.diagnoses[0].id == "indexing_pressure:saturation"
    assert res.impacts[0].id == "writes_rejected"


def test_histogram_render_cache_recomputes_only_when_dirty():
    reg = MetricsRegistry(clock=_Clock())
    reg.observe("lat_ms", 5.0)
    h = reg._metrics[("lat_ms", _label_key({}))]
    d1 = h.to_dict()
    d2 = h.to_dict()
    assert d1["buckets"] == d2["buckets"]
    assert h.renders == 1               # second render served from cache
    reg.observe("lat_ms", 50.0)
    d3 = h.to_dict()
    assert h.renders == 2               # dirtied -> one recompute
    assert d3["count"] == 2
    # cumulative le_* semantics survive the caching
    assert all(d3["buckets"][k] <= d3["count"] for k in d3["buckets"])


# ---------------------------------------------------------------------------
# stalled-progress watchdog (unit)
# ---------------------------------------------------------------------------


class _StubTask:
    def __init__(self, tid, clock, started_at, action="indices:data/read",
                 profile_stage=None):
        self.id = tid
        self.action = action
        self.profile_stage = profile_stage
        self._clock = clock
        self._started = started_at

    def running_time_nanos(self):
        return int((self._clock() - self._started) * 1e9)


def test_watchdog_task_stall_transition_counts_once():
    clock = _Clock(0.0)
    reg = MetricsRegistry(clock=clock)
    task = _StubTask(7, clock, started_at=0.0, profile_stage="fetch")
    tasks = [task]
    wd = StalledProgressWatchdog(
        clock=clock, metrics=reg, tasks_fn=lambda: tasks,
        stall_after_s=30.0, task_deadline_s=120.0)
    clock.advance(60)
    assert wd.sweep() == []             # under deadline: not tracked yet
    clock.advance(61)                   # t=121: past deadline, fp recorded
    assert wd.sweep() == []
    clock.advance(31)                   # unchanged profile_stage for 31s
    findings = wd.sweep()
    assert len(findings) == 1
    f = findings[0]
    assert f["kind"] == "task" and f["resource"] == "task:7"
    assert f["stalled_for_s"] >= 30.0
    assert f["detail"]["profile_stage"] == "fetch"
    assert reg.get_value("watchdog.stalls", kind="task") == 1
    clock.advance(10)
    assert len(wd.sweep()) == 1         # still stalled...
    assert reg.get_value("watchdog.stalls", kind="task") == 1  # ...one trip
    # progress (stage change) clears the stall
    task.profile_stage = "reduce"
    assert wd.sweep() == []
    # vanished tasks stop being tracked
    tasks.clear()
    wd.sweep()
    assert wd.stats()["tracked"] == 0


def test_watchdog_state_lag_constant_vs_shrinking():
    clock = _Clock(0.0)
    reg = MetricsRegistry(clock=clock)
    lags = {"dn-1": 5, "dn-2": 3}
    wd = StalledProgressWatchdog(
        clock=clock, metrics=reg, lag_fn=lambda: lags, stall_after_s=20.0)
    wd.sweep()
    clock.advance(10)
    lags["dn-2"] = 1                    # dn-2 is catching up
    wd.sweep()
    clock.advance(15)                   # dn-1 constant at 5 for 25s
    findings = wd.sweep()
    assert [f["resource"] for f in findings] == ["dn-1"]
    assert findings[0]["kind"] == "cluster_state_lag"
    assert findings[0]["detail"]["versions_behind"] == 5
    assert reg.get_value("watchdog.stalls", kind="cluster_state_lag") == 1
    # caught-up followers (lag 0) leave tracking entirely
    lags["dn-1"] = 0
    lags["dn-2"] = 0
    assert wd.sweep() == []
    assert wd.stats()["tracked"] == 0


# ---------------------------------------------------------------------------
# merge_node_reports (pure-function composition)
# ---------------------------------------------------------------------------


def _node_report(node, status, symptom, resources=()):
    ind = {"status": status, "symptom": symptom, "details": {"n": node}}
    if status != "green":
        ind["diagnosis"] = [{
            "id": "shards_availability:replica_unassigned",
            "cause": "c", "action": "a",
            "affected_resources": sorted(resources)}]
    return {"node": node, "status": status,
            "indicators": {"shards_availability": ind}}


def test_merge_worst_wins_and_diagnosis_resources_union():
    merged = merge_node_reports({
        "dn-0": _node_report("dn-0", "green", "all good"),
        "dn-1": _node_report("dn-1", "yellow", "1 copy missing", ["idx-b"]),
        "dn-2": _node_report("dn-2", "yellow", "2 copies missing",
                             ["idx-a", "idx-b"]),
    })
    assert merged["status"] == "yellow"
    ind = merged["indicators"]["shards_availability"]
    # symptom from the first (sorted) node at the worst status
    assert ind["symptom"] == "1 copy missing"
    assert sorted(ind["details"]["nodes"]) == ["dn-0", "dn-1", "dn-2"]
    assert ind["diagnosis"][0]["affected_resources"] == ["idx-a", "idx-b"]
    assert "node_failures" not in merged


def test_merge_failures_cap_green_to_unknown():
    merged = merge_node_reports(
        {"dn-0": _node_report("dn-0", "green", "ok")},
        node_failures=[{"node": "dn-1", "error": "disconnected"}])
    assert merged["status"] == "unknown"
    assert merged["node_failures"] == [
        {"node": "dn-1", "error": "disconnected"}]
    # ...but real degradation is NOT masked down to unknown
    merged = merge_node_reports(
        {"dn-0": _node_report("dn-0", "red", "primaries down")},
        node_failures=[{"node": "dn-1", "error": "disconnected"}])
    assert merged["status"] == "red"


def test_merge_is_arrival_order_independent():
    a = _node_report("dn-0", "yellow", "y", ["i1"])
    b = _node_report("dn-1", "red", "r", ["i2"])
    m1 = merge_node_reports({"dn-0": a, "dn-1": b})
    m2 = merge_node_reports({"dn-1": b, "dn-0": a})
    assert json.dumps(m1, sort_keys=True) == json.dumps(m2, sort_keys=True)


def test_shard_availability_summary_shapes():
    # no routing table: green by construction (single-process node)
    s = shard_availability_summary(None)
    assert s["status"] == "green" and s["active_shards"] == 0


# ---------------------------------------------------------------------------
# multi-node chaos: fan-out, breaker squeeze, mid-recovery stall, replay
# ---------------------------------------------------------------------------


def _report(cluster, master, indicator=None):
    return cluster.call(master.health_report, indicator)


def _trip_request_breaker(cn, times=6):
    b = cn.breaker_service.get_breaker(CircuitBreaker.REQUEST)
    for _ in range(times):
        with pytest.raises(CircuitBreakingException):
            b.add_estimate_bytes_and_maybe_break(10 ** 15, "health-squeeze")


@pytest.mark.chaos(seed=29)
def test_fan_out_composes_three_nodes(tmp_path, chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    r = _report(c, m)
    assert sorted(r["indicators"]) == sorted(INDICATOR_NAMES)
    for name, ind in r["indicators"].items():
        assert sorted(ind["details"]["nodes"]) == ["dn-0", "dn-1", "dn-2"], \
            f"seed={chaos_seed}: {name} missing nodes"
    # cluster_health reads the same availability impl as the indicator
    c.call(m.create_index, "logs", number_of_shards=2, number_of_replicas=1)
    c.run_for(60)
    h = m.cluster_health()
    assert h["status"] == "green" and h["active_shards"] == 4
    assert h["number_of_nodes"] == 3 and h["number_of_data_nodes"] == 3
    r = _report(c, m, "shards_availability")
    assert r["indicators"]["shards_availability"]["status"] == h["status"]


@pytest.mark.chaos(seed=37)
def test_unallocatable_replicas_yellow_everywhere(tmp_path, chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    # 4 copies per shard on 3 nodes: one replica can never allocate
    c.call(m.create_index, "few", number_of_shards=1, number_of_replicas=3)
    c.run_for(90)
    h = m.cluster_health()
    assert h["status"] == "yellow", f"seed={chaos_seed}: {h}"
    assert h["unassigned_shards"] == 1
    r = _report(c, m, "shards_availability")
    ind = r["indicators"]["shards_availability"]
    assert ind["status"] == "yellow"
    diag = ind["diagnosis"][0]
    assert diag["id"] == "shards_availability:replica_unassigned"
    assert diag["affected_resources"] == ["few"]
    assert ind["impacts"][0]["id"] == "replica_unassigned"


@pytest.mark.chaos(seed=7)
def test_breaker_squeeze_red_with_pinned_diagnosis_then_recovers(
        tmp_path, chaos_seed):
    """Seeded breaker squeeze: the trip *rate* turns the indicator red
    with the exact typed-diagnosis shape; once the storm leaves the
    trailing window the indicator walks back to green on its own."""
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    c.run_for(11)                       # lay a pre-squeeze ring sample
    r = _report(c, m, "circuit_breakers")
    assert r["status"] == "green", f"seed={chaos_seed}: {r}"

    _trip_request_breaker(m, times=6)
    c.run_for(10)                       # next sample catches the trips
    r = _report(c, m, "circuit_breakers")
    ind = r["indicators"]["circuit_breakers"]
    assert ind["status"] == "red", f"seed={chaos_seed}: {ind['symptom']}"
    assert "tripped 6 time(s)" in ind["symptom"]
    # pinned diagnosis/impact shape — the typed contract tooling reads
    assert ind["diagnosis"] == [{
        "id": "circuit_breakers:pressure",
        "cause": "memory accounting is at or over breaker limits",
        "action": "reduce concurrent request sizes, raise "
                  "indices.breaker.*.limit, or add capacity",
        "affected_resources": [],
    }], f"seed={chaos_seed}"
    assert [i["id"] for i in ind["impacts"]] == ["requests_rejected"]
    # the squeezed node is the red one; peers stayed green (their
    # details carry no trips in-window)
    det = ind["details"]["nodes"][m.local_node.node_id]
    assert det["recent_trips"] == 6.0
    assert m.breaker_service.get_breaker(
        CircuitBreaker.REQUEST).used == 0   # squeeze retained no bytes

    # no further trips: keep sampling until the storm ages out of the
    # 60s window, then the verdict recovers without intervention
    for _ in range(8):
        c.run_for(10)
        r = _report(c, m, "circuit_breakers")
    assert r["status"] == "green", f"seed={chaos_seed}: {r}"


@pytest.mark.chaos(seed=2)
def test_node_kill_mid_recovery_trips_watchdog(tmp_path, chaos_seed):
    """Blackhole the recovery source<->target link: bytes stop moving
    while both nodes stay in the cluster — exactly the stall a
    point-in-time `_recovery` view cannot see."""
    c = SimDataCluster(3, tmp_path, seed=chaos_seed,
                       settings={"health.watchdog.stall_after": 5.0})
    m = c.stabilise()
    c.call(m.create_index, "logs", number_of_shards=1, number_of_replicas=1)
    c.run_for(60)
    _index_some_docs(c, m, n=20)

    # this seed pins the topology the fault needs: the primary (every
    # recovery's SOURCE) on a non-master node and the replica on the
    # master, so blackholing primary<->free-node touches neither the
    # master's publish path nor fault detection
    master_id = m.local_node.node_id
    irt = c.master().state.routing_table.index("logs").shard(0)
    src = irt.primary.current_node_id
    occupied = sorted(s.current_node_id for s in irt.shards)
    tgt = next(n.node_id for n in c.nodes if n.node_id not in occupied)
    assert src != master_id and tgt != master_id, \
        f"seed={chaos_seed} no longer pins primary/replica placement"
    replica_holder = master_id

    # cut the link FIRST, then move the replica onto the free node:
    # the master's publish reaches the target over a healthy link, the
    # target opens its RecoveryState and enters stage "index", and its
    # start_recovery request to the primary vanishes — a live recovery
    # frozen at zero bytes
    src_node = next(n for n in c.nodes if n.node_id == src)
    tgt_node = next(n for n in c.nodes if n.node_id == tgt)
    c.network.isolate(src_node, [tgt_node], BLACKHOLE)

    c.call(m.reroute, commands=[{"move": {
        "index": "logs", "shard": 0,
        "from_node": replica_holder, "to_node": tgt}}])
    c.run_for(0.5)
    tgt_cn = c.cluster_nodes[tgt]
    live = [rec for rec in tgt_cn.data_node.recoveries.values()
            if rec.stage not in ("done", "failed", "cancelled")]
    assert live, f"seed={chaos_seed}: no live recovery on target"
    assert live[0].recovered_bytes == 0

    r1 = tgt_cn.health.local_report("recovery_progress")
    assert r1["indicators"]["recovery_progress"]["status"] == "yellow"
    c.run_for(6)                        # > stall_after with frozen bytes
    r2 = tgt_cn.health.local_report("recovery_progress")
    ind = r2["indicators"]["recovery_progress"]
    assert ind["status"] == "red", f"seed={chaos_seed}: {ind}"
    assert ind["diagnosis"][0]["id"] == "recovery_progress:stalled"
    assert ind["diagnosis"][0]["affected_resources"] == ["logs[0]"]
    stalled = ind["details"]["stalled"]
    assert stalled and stalled[0]["resource"] == "logs[0]"
    assert stalled[0]["stalled_for_s"] >= 5.0
    # counter bumped exactly once, on the transition into stalled
    assert tgt_cn.telemetry.metrics.get_value(
        "watchdog.stalls", kind="recovery") == 1
    tgt_cn.health.local_report("recovery_progress")
    assert tgt_cn.telemetry.metrics.get_value(
        "watchdog.stalls", kind="recovery") == 1

    # heal: the watchdog never killed anything — the wedged
    # start_recovery times out (120s), the copy re-allocates, and the
    # verdict leaves red on its own
    c.network.heal()
    c.run_for(200)
    r3 = tgt_cn.health.local_report("recovery_progress")
    assert r3["indicators"]["recovery_progress"]["status"] != "red", \
        f"seed={chaos_seed}: {r3}"


@pytest.mark.chaos(seed=41)
def test_fan_out_node_failures_for_unreachable_node(tmp_path, chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    victim = next(n for n in c.nodes
                  if n.node_id != m.local_node.node_id)
    c.network.isolate(
        victim, [n for n in c.nodes if n.node_id != victim.node_id],
        DISCONNECTED)
    r = _report(c, m)
    assert [f["node"] for f in r["node_failures"]] == [victim.node_id], \
        f"seed={chaos_seed}: {r.get('node_failures')}"
    # two nodes answered; the hole caps confidence below green
    assert r["status"] == "unknown"
    for ind in r["indicators"].values():
        assert victim.node_id not in ind["details"]["nodes"]


@pytest.mark.chaos(seed=23)
def test_same_seed_health_reports_byte_identical(tmp_path, chaos_seed):
    """Two runs of the same seeded scenario render the same report
    bytes. device_engine is excluded: its compile totals read the
    process-global XLA tracker, which is interpreter state shared
    across runs in one process, not seed state."""

    def run_once(root):
        c = SimDataCluster(3, root, seed=chaos_seed)
        m = c.stabilise()
        c.call(m.create_index, "logs",
               number_of_shards=2, number_of_replicas=1)
        c.run_for(60)
        _index_some_docs(c, m, n=10)
        _trip_request_breaker(m, times=6)
        c.run_for(12)
        r = _report(c, m)
        r["indicators"].pop("device_engine")
        return json.dumps(r, sort_keys=True)

    assert run_once(tmp_path / "a") == run_once(tmp_path / "b")
