"""Security round 2: realm chain, token service, PKI realm +
delegate_pki, role mappings, audit log (ref: AuthenticationService,
TokenService, PkiRealm, LoggingAuditTrail test disciplines)."""

import base64
import json
import os
import subprocess

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "audit": {"enabled": True},
            # header-carried certs are trusted only behind a
            # TLS-terminating proxy — explicit opt-in
            "authc": {"pki": {"trust_proxy_header": True}}}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))
    yield n
    n.close()


def basic(user, pw):
    return {"Authorization": "Basic "
            + base64.b64encode(f"{user}:{pw}".encode()).decode()}


def call(node, method, path, body=None, headers=None, expect=200, **params):
    status, r = node.rest_controller.dispatch(method, path, params, body,
                                              headers=headers)
    assert status == expect, (status, r)
    return r


ELASTIC = None


def test_token_lifecycle(node):
    h = basic("elastic", "s3cret")
    # password grant
    r = call(node, "POST", "/_security/oauth2/token",
             {"grant_type": "password", "username": "elastic",
              "password": "s3cret"}, headers=h)
    access, refresh = r["access_token"], r["refresh_token"]
    assert r["type"] == "Bearer" and r["expires_in"] == 1200

    # bearer authenticates through the token realm
    me = call(node, "GET", "/_security/_authenticate",
              headers={"Authorization": f"Bearer {access}"})
    assert me["username"] == "elastic"

    # refresh rotates; the old access token dies
    r2 = call(node, "POST", "/_security/oauth2/token",
              {"grant_type": "refresh_token", "refresh_token": refresh},
              headers=h)
    assert r2["access_token"] != access
    call(node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {access}"}, expect=401)
    call(node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {r2['access_token']}"})
    # a refresh token is single-use
    call(node, "POST", "/_security/oauth2/token",
         {"grant_type": "refresh_token", "refresh_token": refresh},
         headers=h, expect=400)

    # explicit invalidation
    inv = call(node, "DELETE", "/_security/oauth2/token",
               {"token": r2["access_token"]}, headers=h)
    assert inv["invalidated_tokens"] == 1
    call(node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {r2['access_token']}"},
         expect=401)


def test_client_credentials_grant(node):
    h = basic("elastic", "s3cret")
    r = call(node, "POST", "/_security/oauth2/token",
             {"grant_type": "client_credentials"}, headers=h)
    assert "refresh_token" not in r
    me = call(node, "GET", "/_security/_authenticate",
              headers={"Authorization": f"Bearer {r['access_token']}"})
    assert me["username"] == "elastic"


def _make_cert(tmp_path, cn):
    key = tmp_path / f"{cn}.key"
    crt = tmp_path / f"{cn}.crt"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", f"/C=US/O=Acme/CN={cn}"],
        check=True, capture_output=True)
    return crt.read_text()


def _make_ca_signed_cert(tmp_path, cn, ca="testca"):
    """CA cert + a client cert SIGNED by that CA (the delegated-PKI
    trust-chain contract). Returns (ca_pem_path, client_pem_text)."""
    ca_key, ca_crt = tmp_path / f"{ca}.key", tmp_path / f"{ca}.crt"
    if not ca_crt.exists():
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
             "-subj", f"/C=US/O=Acme/CN={ca}"],
            check=True, capture_output=True)
    key, csr, crt = (tmp_path / f"{cn}.key", tmp_path / f"{cn}.csr",
                     tmp_path / f"{cn}-signed.crt")
    subprocess.run(
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(csr),
         "-subj", f"/C=US/O=Acme/CN={cn}"],
        check=True, capture_output=True)
    subprocess.run(
        ["openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
         "-CAkey", str(ca_key), "-CAcreateserial", "-days", "1",
         "-out", str(crt)],
        check=True, capture_output=True)
    return str(ca_crt), crt.read_text()


def _pem_to_der_b64(pem):
    return "".join(line for line in pem.splitlines()
                   if not line.startswith("-----"))


def test_pki_realm_and_delegate(tmp_path):
    ca_path, pem = _make_ca_signed_cert(tmp_path, "kibana-client")
    node = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"pki": {"trust_proxy_header": True,
                              "truststore": ca_path}}}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))
    try:
        # map the DN to roles (ref: role mapping API driving PKI realms)
        call(node, "PUT", "/_security/role_mapping/pki-map",
             {"roles": ["monitoring_user"],
              "rules": {"field": {"dn": "CN=kibana-client,*"}}},
             headers=basic("elastic", "s3cret"))

        # direct header-based PKI (TLS-terminating proxy convention)
        me = call(node, "GET", "/_security/_authenticate",
                  headers={"x-ssl-client-cert": pem})
        assert me["username"] == "kibana-client"
        assert "monitoring_user" in me["roles"]

        # delegated PKI: CA-signed DER chain → access token
        r = call(node, "POST", "/_security/delegate_pki",
                 {"x509_certificate_chain": [_pem_to_der_b64(pem)]},
                 headers=basic("elastic", "s3cret"))
        assert r["authentication"]["username"] == "kibana-client"
        me = call(node, "GET", "/_security/_authenticate",
                  headers={"Authorization":
                           f"Bearer {r['access_token']}"})
        assert me["username"] == "kibana-client"

        # a SELF-SIGNED cert (not chained to the truststore) is REFUSED
        # for delegation — any DN could otherwise be fabricated (ref:
        # PkiRealm 'Certificate for <dn> is not trusted')
        forged = _make_cert(tmp_path, "forged-admin")
        call(node, "POST", "/_security/delegate_pki",
             {"x509_certificate_chain": [_pem_to_der_b64(forged)]},
             headers=basic("elastic", "s3cret"), expect=401)

        # an unmapped cert authenticates with no roles → reads fail
        pem2 = _make_cert(tmp_path, "stranger")
        call(node, "GET", "/_cluster/health",
             headers={"x-ssl-client-cert": pem2}, expect=403)
    finally:
        node.close()


def test_delegate_pki_rejects_rogue_issuer_with_trusted_dn(tmp_path):
    """A rogue in-chain 'CA' that merely COPIES the trusted CA's subject
    DN (attacker's own key) must not anchor the chain — trust is a key
    verification, never a DN string match."""
    ca_path, _ = _make_ca_signed_cert(tmp_path, "legit-client")
    node = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"pki": {"truststore": ca_path}}}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))
    try:
        # rogue CA: same subject DN as the trusted CA, different key
        rk, rc = tmp_path / "rogue.key", tmp_path / "rogue.crt"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(rk), "-out", str(rc), "-days", "1",
             "-subj", "/C=US/O=Acme/CN=testca"],
            check=True, capture_output=True)
        lk, lcsr, lc = (tmp_path / "victim.key", tmp_path / "victim.csr",
                        tmp_path / "victim.crt")
        subprocess.run(
            ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(lk), "-out", str(lcsr),
             "-subj", "/C=US/O=Acme/CN=any-victim"],
            check=True, capture_output=True)
        subprocess.run(
            ["openssl", "x509", "-req", "-in", str(lcsr), "-CA", str(rc),
             "-CAkey", str(rk), "-CAcreateserial", "-days", "1",
             "-out", str(lc)],
            check=True, capture_output=True)
        chain = [_pem_to_der_b64(lc.read_text()),
                 _pem_to_der_b64(rc.read_text())]
        call(node, "POST", "/_security/delegate_pki",
             {"x509_certificate_chain": chain},
             headers=basic("elastic", "s3cret"), expect=401)

        # a forged SELF-SIGNED cert whose subject copies the trusted
        # CA's DN must also fail (no self-anchoring by subject match)
        forged = tmp_path / "forged-ca.crt"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(tmp_path / "f.key"), "-out", str(forged),
             "-days", "1", "-subj", "/C=US/O=Acme/CN=testca"],
            check=True, capture_output=True)
        call(node, "POST", "/_security/delegate_pki",
             {"x509_certificate_chain":
              [_pem_to_der_b64(forged.read_text())]},
             headers=basic("elastic", "s3cret"), expect=401)

        # malformed base64 is a 4xx, not a 500
        call(node, "POST", "/_security/delegate_pki",
             {"x509_certificate_chain": ["ab!c"]},
             headers=basic("elastic", "s3cret"), expect=401)
    finally:
        node.close()


def test_delegate_pki_refused_without_truststore(node, tmp_path):
    """No configured truststore ⇒ delegated PKI is refused outright
    (the reference refuses delegation without a trust manager)."""
    pem = _make_cert(tmp_path, "anyone")
    call(node, "POST", "/_security/delegate_pki",
         {"x509_certificate_chain": [_pem_to_der_b64(pem)]},
         headers=basic("elastic", "s3cret"), expect=401)


def test_role_mapping_crud(node):
    h = basic("elastic", "s3cret")
    r = call(node, "PUT", "/_security/role_mapping/m1",
             {"roles": ["superuser"],
              "rules": {"all": [{"field": {"username": "admin-*"}},
                                {"field": {"realm.name": "pki1"}}]}},
             headers=h)
    assert r["role_mapping"]["created"]
    got = call(node, "GET", "/_security/role_mapping/m1", headers=h)
    assert got["m1"]["roles"] == ["superuser"]
    assert call(node, "DELETE", "/_security/role_mapping/m1",
                headers=h)["found"]
    call(node, "GET", "/_security/role_mapping/m1", headers=h, expect=404)


def test_pki_header_untrusted_by_default(tmp_path):
    """Without the trust_proxy_header opt-in, a header-carried cert is
    IGNORED (an unverified cert must never authenticate by itself)."""
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {"enabled": True}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "plainnode"))
    try:
        pem = _make_cert(tmp_path, "forged-admin")
        call(n, "GET", "/_security/_authenticate",
             headers={"x-ssl-client-cert": pem}, expect=401)
    finally:
        n.close()


def test_invalidate_by_username_needs_privilege(node):
    h = basic("elastic", "s3cret")
    call(node, "PUT", "/_security/user/lowly",
         {"password": "lowlypass1", "roles": ["monitoring_user"]},
         headers=h)
    call(node, "POST", "/_security/oauth2/token",
         {"grant_type": "password", "username": "elastic",
          "password": "s3cret"}, headers=h)
    # a non-privileged user may NOT revoke another user's tokens...
    call(node, "DELETE", "/_security/oauth2/token",
         {"username": "elastic"}, headers=basic("lowly", "lowlypass1"),
         expect=403)
    # ...but may revoke their own
    mine = call(node, "POST", "/_security/oauth2/token",
                {"grant_type": "password", "username": "lowly",
                 "password": "lowlypass1"},
                headers=basic("lowly", "lowlypass1"))
    r = call(node, "DELETE", "/_security/oauth2/token",
             {"username": "lowly"}, headers=basic("lowly", "lowlypass1"))
    assert r["invalidated_tokens"] >= 1
    call(node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {mine['access_token']}"},
         expect=401)


def test_realm_chain_order_and_failure(node):
    # wrong basic creds fail with 401 even though other realms exist
    call(node, "GET", "/_security/_authenticate",
         headers=basic("elastic", "wrong"), expect=401)
    # garbage bearer fails in the token realm
    call(node, "GET", "/_security/_authenticate",
         headers={"Authorization": "Bearer nope"}, expect=401)


def test_audit_log_events(node, tmp_path):
    audit_path = os.path.join(str(tmp_path / "data"), "_audit.log")
    call(node, "GET", "/_cluster/health", headers=basic("elastic", "s3cret"))
    call(node, "GET", "/_cluster/health", headers=basic("elastic", "bad"),
         expect=401)
    # limited user: authenticated but denied
    call(node, "PUT", "/_security/user/peon",
         {"password": "peonpass1", "roles": ["monitoring_user"]},
         headers=basic("elastic", "s3cret"))
    call(node, "PUT", "/_security/role_mapping/x", {"roles": []},
         headers=basic("peon", "peonpass1"), expect=403)

    events = [json.loads(line) for line in open(audit_path)]
    actions = [e["event.action"] for e in events]
    assert "authentication_success" in actions
    assert "authentication_failed" in actions
    assert "access_granted" in actions
    assert "access_denied" in actions
    denied = [e for e in events if e["event.action"] == "access_denied"]
    assert denied[-1]["user.name"] == "peon"
    ok = [e for e in events
          if e["event.action"] == "authentication_success"]
    assert ok[0]["realm"] == "native1"


# ----------------------------------------------------- file + JWT realms

def test_file_realm(node, tmp_path):
    from elasticsearch_tpu.xpack.security import _hash_password
    data = str(tmp_path / "data")
    with open(os.path.join(data, "users"), "w") as f:
        f.write("# users file\nfiona:" + _hash_password("filepass1") + "\n")
    with open(os.path.join(data, "users_roles"), "w") as f:
        f.write("monitoring_user:fiona\n")
    me = call(node, "GET", "/_security/_authenticate",
              headers=basic("fiona", "filepass1"))
    assert me["username"] == "fiona"
    assert me["roles"] == ["monitoring_user"]
    call(node, "GET", "/_security/_authenticate",
         headers=basic("fiona", "wrong"), expect=401)


def _hs256(claims, key):
    import hashlib
    import hmac as _hmac

    def enc(obj):
        raw = json.dumps(obj, separators=(",", ":")).encode()
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    head = enc({"alg": "HS256", "typ": "JWT"})
    body = enc(claims)
    sig = _hmac.new(key, f"{head}.{body}".encode(),
                    hashlib.sha256).digest()
    return f"{head}.{body}." + \
        base64.urlsafe_b64encode(sig).rstrip(b"=").decode()


@pytest.fixture()
def jwt_node(tmp_path):
    from elasticsearch_tpu.common.keystore import (KEYSTORE_FILENAME,
                                                   KeyStore)
    from elasticsearch_tpu.common.settings import Settings
    data = tmp_path / "jwtdata"
    data.mkdir()
    ks = KeyStore.create(str(data / KEYSTORE_FILENAME), "")
    ks.set_string("xpack.security.authc.jwt.hmac_key", "jwt-hmac-secret")
    ks.set_string("bootstrap.password", "s3cret")
    ks.save("")
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"jwt": {"allowed_issuer": "https://idp.test"}}}},
    }), data_path=str(data))
    yield n
    n.close()


def test_jwt_realm(jwt_node):
    import time as _time
    key = b"jwt-hmac-secret"
    good = _hs256({"sub": "svc-bot", "iss": "https://idp.test",
                   "exp": _time.time() + 600,
                   "roles": ["monitoring_user"]}, key)
    me = call(jwt_node, "GET", "/_security/_authenticate",
              headers={"Authorization": f"Bearer {good}"})
    assert me["username"] == "svc-bot"
    assert "monitoring_user" in me["roles"]
    # JWT users pass authorization with their claimed roles
    call(jwt_node, "GET", "/_cluster/health",
         headers={"Authorization": f"Bearer {good}"})

    expired = _hs256({"sub": "svc-bot", "iss": "https://idp.test",
                      "exp": _time.time() - 5}, key)
    call(jwt_node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {expired}"}, expect=401)
    wrong_iss = _hs256({"sub": "x", "iss": "https://evil.test",
                        "exp": _time.time() + 600}, key)
    call(jwt_node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {wrong_iss}"}, expect=401)
    forged = _hs256({"sub": "admin", "iss": "https://idp.test",
                     "exp": _time.time() + 600}, b"other-key")
    call(jwt_node, "GET", "/_security/_authenticate",
         headers={"Authorization": f"Bearer {forged}"}, expect=401)


# ------------------------------------------------------------- HTTPS

def test_https_rest_endpoint(tmp_path):
    """xpack.security.http.ssl: the HTTP layer serves TLS (ref:
    SecurityNetty4HttpServerTransport); plaintext clients are refused,
    the typed client connects with the CA."""
    import subprocess as sp
    from elasticsearch_tpu.client import Elasticsearch, ConnectionError_

    crt = tmp_path / "http.crt"
    key = tmp_path / "http.key"
    sp.run(["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(crt), "-days", "1",
            "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1"],
           check=True, capture_output=True)
    node = Node(settings=Settings.from_dict({
        "xpack": {"security": {"http": {"ssl": {
            "enabled": True, "certificate": str(crt),
            "key": str(key)}}}},
    }), data_path=str(tmp_path / "tls"))
    try:
        port = node.start(0)
        es = Elasticsearch([f"https://127.0.0.1:{port}"],
                           ca_certs=str(crt))
        assert es.ping()
        es.indices.create("t")
        es.index("t", {"x": 1}, id="1", refresh=True)
        assert es.count("t")["count"] == 1

        # plaintext against the TLS port fails
        plain = Elasticsearch([f"http://127.0.0.1:{port}"],
                              max_retries=2)
        assert plain.ping() is False
    finally:
        node.close()


def test_transport_tls_mutual(tmp_path):
    """xpack.security.transport.ssl: node-to-node TLS with mutual cert
    verification — a node without the right cert cannot join the
    conversation (ref: SecurityNetty4ServerTransport)."""
    import subprocess as sp
    import threading as _t
    from elasticsearch_tpu.transport.transport import (
        ConnectTransportException,
        DiscoveryNode,
        TcpTransport,
        TransportService,
    )

    crt = tmp_path / "node.crt"
    key = tmp_path / "node.key"
    sp.run(["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(crt), "-days", "1",
            "-subj", "/CN=transport"], check=True, capture_output=True)
    ssl_cfg = {"certificate": str(crt), "key": str(key),
               "certificate_authorities": str(crt)}

    a = TransportService(TcpTransport(
        DiscoveryNode(node_id="a", name="a", host="127.0.0.1"),
        ssl_config=ssl_cfg))
    b = TransportService(TcpTransport(
        DiscoveryNode(node_id="b", name="b", host="127.0.0.1"),
        ssl_config=ssl_cfg))
    got = {}
    done = _t.Event()
    b.register_request_handler(
        "test:echo", lambda req, ch, src: ch.send_response(
            {"echo": req["msg"]}))
    try:
        from elasticsearch_tpu.transport.transport import ResponseHandler
        a.send_request(b.local_node, "test:echo", {"msg": "over-tls"},
                       ResponseHandler(
                           lambda r: (got.update(r), done.set()),
                           lambda e: (got.update(err=e), done.set())),
                       timeout=10.0)
        assert done.wait(10) and got.get("echo") == "over-tls", got

        # a node WITHOUT certs must NOT get a response (mutual TLS)
        plain = TransportService(TcpTransport(
            DiscoveryNode(node_id="c", name="c", host="127.0.0.1")))
        try:
            outcome = {}
            d2 = _t.Event()
            try:
                plain.send_request(
                    b.local_node, "test:echo", {"msg": "nope"},
                    ResponseHandler(
                        lambda r: (outcome.update(ok=r), d2.set()),
                        lambda e: (outcome.update(err=e), d2.set())),
                    timeout=3.0)
            except ConnectTransportException:
                outcome["err"] = "connect refused"
                d2.set()
            d2.wait(8)
            assert "ok" not in outcome, (
                f"plaintext node got a response through mTLS: {outcome}")
        finally:
            plain.close()
    finally:
        a.close()
        b.close()
