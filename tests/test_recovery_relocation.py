"""Shard relocation & staged recovery under the deterministic harness:
explicit `_cluster/reroute` moves, node drain via allocation exclusion,
relocation under live write/search load with zero acked-write loss,
chaos (source death mid-phase-1, cross-node cancel mid-replay), and a
mixed-wire rolling-upgrade smoke (ref strategy: RelocationIT /
IndexRecoveryIT / SearchWhileRelocatingIT on the AbstractCoordinator
simulation).

Every chaos path replays byte-identically from its queue seed."""

import pytest

from test_cluster_node import SimDataCluster, _index_some_docs

from elasticsearch_tpu.cluster.state import (
    SHARD_RELOCATING,
    SHARD_STARTED,
)
from elasticsearch_tpu.testing.deterministic import DISCONNECTED
from elasticsearch_tpu.transport.transport import CURRENT_VERSION
from elasticsearch_tpu.utils.breaker import CircuitBreaker


@pytest.fixture()
def cluster(tmp_path):
    return SimDataCluster(3, tmp_path, seed=31)


# ------------------------------------------------------------------ helpers

def _shard_table(cluster, index="logs", shard_id=0):
    irt = cluster.master().state.routing_table.index(index)
    assert irt is not None, f"no routing for [{index}]"
    return irt.shard(shard_id)


def _primary_node_id(cluster, index="logs", shard_id=0):
    primary = _shard_table(cluster, index, shard_id).primary
    assert primary is not None and primary.active
    return primary.current_node_id


def _other_data_node_id(cluster, *occupied):
    for node in cluster.nodes:
        if node.node_id not in occupied:
            return node.node_id
    raise AssertionError("no free node")


def _doc_count(cluster, coordinator, index="logs"):
    resp = cluster.call(coordinator.search, index,
                        {"query": {"match_all": {}}, "size": 0})
    assert resp["_shards"]["failed"] == 0, resp
    return resp["hits"]["total"]["value"]


def _move(cluster, index, shard_id, from_node, to_node, **kwargs):
    return cluster.call(
        cluster.master().reroute,
        commands=[{"move": {"index": index, "shard": shard_id,
                            "from_node": from_node,
                            "to_node": to_node}}], **kwargs)


def _relocation_recs(cn, index="logs", shard_id=0):
    return [rec for (ix, sid, _alloc), rec in
            sorted(cn.data_node.recoveries.items())
            if ix == index and sid == shard_id
            and rec.recovery_type == "relocation"]


def _no_relocating_copies(cluster, index="logs"):
    return not any(s.state == SHARD_RELOCATING for s in
                   cluster.master().state.routing_table.all_shards()
                   if s.index == index)


# ------------------------------------------------------------- explicit move

def test_explicit_move_relocates_shard(cluster):
    """`POST /_cluster/reroute {move}`: RELOCATING source +
    INITIALIZING target pair, staged handoff, source copy removed and
    the target serving searches with full seqno continuity."""
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=20)

    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    resp = _move(cluster, "logs", 0, src, tgt)
    assert resp["acknowledged"] is True
    cluster.run_for(60)

    table = _shard_table(cluster)
    assert [s.state for s in table.shards] == [SHARD_STARTED]
    assert table.primary.current_node_id == tgt
    # the source node dropped its copy entirely
    assert ("logs", 0) not in cluster.cluster_nodes[src].data_node.shards
    # the target holds the shard with seqno continuity: 20 docs means
    # local checkpoint 19, and the new primary's tracker agrees
    tgt_shard = cluster.cluster_nodes[tgt].data_node.shards[("logs", 0)]
    assert tgt_shard.primary and tgt_shard.state == "started"
    assert tgt_shard.engine.tracker.checkpoint == 19
    assert tgt_shard.tracker is not None
    assert tgt_shard.tracker.global_checkpoint == 19

    assert _doc_count(cluster, master) == 20

    recs = _relocation_recs(cluster.cluster_nodes[tgt])
    assert len(recs) == 1
    rec = recs[0].to_dict()
    assert rec["stage"] == "DONE"
    assert rec["protocol"] == CURRENT_VERSION
    assert rec["index_files"]["recovered_bytes"] > 0
    assert rec["index_files"]["recovered_bytes"] == \
        rec["index_files"]["total_bytes"]
    assert rec["source_node"] == \
        cluster.cluster_nodes[src].local_node.name
    assert rec["target_node"] == \
        cluster.cluster_nodes[tgt].local_node.name
    assert rec["total_time_ms"] is not None and rec["failure"] is None

    # the source's retention lease for this recovery was released
    assert not any(cn.data_node.shards.get(("logs", 0)) and
                   cn.data_node.shards[("logs", 0)].tracker and
                   any(lid.startswith("peer_recovery/") for lid in
                       cn.data_node.shards[("logs", 0)]
                       .tracker.get_retention_leases())
                   for nid, cn in cluster.cluster_nodes.items()
                   if nid != tgt)


def test_reroute_explain_dry_run_reports_decider_decisions(cluster):
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    resp = _move(cluster, "logs", 0, src, tgt, explain=True,
                 dry_run=True)
    assert resp["acknowledged"] is True
    (entry,) = resp["explanations"]
    assert entry["command"] == "move" and entry["accepted"] is True
    deciders = {d["decider"] for d in entry["decisions"]}
    assert "same_shard" in deciders and "throttling" in deciders
    # dry_run: nothing actually moved
    cluster.run_for(10)
    assert _primary_node_id(cluster) == src


def test_reroute_cancel_reverts_relocation(cluster):
    """`{cancel}` on the relocation target drops the INITIALIZING copy
    and flips the source back to STARTED — no write ever lost."""
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=10)
    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    master.reroute(commands=[{"move": {
        "index": "logs", "shard": 0,
        "from_node": src, "to_node": tgt}}])
    # cancel promptly — as soon as the INITIALIZING target shows up in
    # the published routing, before recovery can complete
    for _ in range(200):
        cluster.run_for(0.05)
        table = _shard_table(cluster)
        if any(s.is_relocation_target for s in table.shards):
            break
    else:
        raise AssertionError("relocation target never appeared")
    cluster.call(master.reroute, commands=[
        {"cancel": {"index": "logs", "shard": 0, "node": tgt,
                    "allow_primary": False}}])
    cluster.run_for(60)
    table = _shard_table(cluster)
    assert [s.state for s in table.shards] == [SHARD_STARTED]
    assert table.primary.current_node_id == src
    assert _doc_count(cluster, master) == 10


# ------------------------------------------------------------- node drain

def test_node_drain_moves_shards_and_restores_hbm_residency(cluster):
    """`cluster.routing.allocation.exclude._id` drains every shard off
    the node; each relocated copy re-uploads its device segments to the
    target's HBM (visible in the recovery's device section and the
    target's device cache) before flipping STARTED."""
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=30)   # refreshes → segments exist

    drained = _primary_node_id(cluster, shard_id=0)
    resp = cluster.call(
        master.update_cluster_settings,
        persistent={"cluster.routing.allocation.exclude._id": drained})
    assert resp["acknowledged"] is True
    cluster.run_for(90)

    state = cluster.master().state
    moved = [s for s in state.routing_table.all_shards()
             if s.index == "logs"]
    assert all(s.state == SHARD_STARTED for s in moved)
    assert all(s.current_node_id != drained for s in moved)
    # the drained node no longer hosts (or serves) any copy
    assert not cluster.cluster_nodes[drained].data_node.shards
    assert _doc_count(cluster, master) == 30

    # device re-residency happened on a target before STARTED
    relocated = [rec for cn in cluster.cluster_nodes.values()
                 for rec in _relocation_recs(cn)
                 + _relocation_recs(cn, shard_id=1)]
    assert relocated, "no relocation recovery recorded"
    assert any(rec.hbm_segments > 0 for rec in relocated)
    by_name = {cn.local_node.name: cn
               for cn in cluster.cluster_nodes.values()}
    for rec in relocated:
        assert rec.stage == "done" and rec.hbm_skipped_segments == 0
        tgt_cache = by_name[rec.target_node].data_node.device_cache
        assert tgt_cache.hbm_stats()["total_bytes"] > 0

    # un-draining re-admits the node for future allocations
    cluster.call(master.update_cluster_settings,
                 persistent={
                     "cluster.routing.allocation.exclude._id": None})
    assert "cluster.routing.allocation.exclude._id" not in \
        cluster.master().state.metadata.persistent_settings


# ------------------------------------------------- relocation under load

def _staggered_bulks(cluster, coordinator, acked, rejected,
                     start=0.0, rounds=12, batch=5, gap=0.35,
                     index="logs"):
    """Schedule `rounds` bulk writes spread across the relocation
    window, recording acked ids; 429/backpressure rejections are the
    client's to retry and land in `rejected`."""
    counter = {"n": 0}

    def one_round():
        items = []
        for _ in range(batch):
            i = counter["n"]
            counter["n"] += 1
            items.append({"op": "index", "id": f"live-{i}",
                          "source": {"body": f"live doc {i}", "n": i}})

        def on_done(resp, err=None, _items=items):
            if err is not None:
                rejected.extend(d["id"] for d in _items)
                return
            for item, d in zip(resp["items"], _items):
                if item and "error" not in item:
                    acked.append(d["id"])
                else:
                    rejected.append(d["id"])

        coordinator.bulk(index, items, on_done=on_done)

    for r in range(rounds):
        cluster.queue.schedule(start + r * gap, one_round,
                               f"live-bulk-{r}")


def _run_relocation_under_load(tmp_path, seed):
    cluster = SimDataCluster(3, tmp_path, seed=seed)
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=20)

    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    acked, rejected, search_totals = [], [], []
    _staggered_bulks(cluster, master, acked, rejected)

    def probe_search():
        master.search("logs", {"query": {"match": {"body": "doc"}},
                               "size": 0},
                      on_done=lambda r, e=None:
                      search_totals.append(
                          "err" if e or r["_shards"]["failed"]
                          else r["hits"]["total"]["value"]))

    for t in (0.5, 1.5, 2.5, 3.5):
        cluster.queue.schedule(t, probe_search, f"probe-{t}")
    _move(cluster, "logs", 0, src, tgt)
    cluster.run_for(90)
    cluster.call(master.refresh)

    table = _shard_table(cluster)
    assert table.primary.current_node_id == tgt
    assert [s.state for s in table.shards] == [SHARD_STARTED]
    # ZERO acked-write loss: every acked doc is searchable post-move
    total = _doc_count(cluster, master)
    assert total == 20 + len(acked), \
        f"acked={len(acked)} rejected={len(rejected)} total={total}"
    assert len(acked) > 0
    # searches during the window never failed (ARS fails over copies)
    assert search_totals and "err" not in search_totals

    tgt_cn = cluster.cluster_nodes[tgt]
    (rec,) = _relocation_recs(tgt_cn)
    assert rec.stage == "done"

    def scrub(d):
        # allocation ids are uuid4 identities, not replay state
        return {k: v for k, v in d.items() if k != "allocation_id"}

    return {"acked": sorted(acked), "rejected": sorted(rejected),
            "search_totals": search_totals, "total": total,
            "recovery": scrub(rec.to_dict()),
            "recoveries": [scrub(r) for r in
                           tgt_cn.data_node.recovery_stats()]}


@pytest.mark.chaos(seed=29)
def test_relocation_under_write_and_search_load(tmp_path):
    """Primary relocation with concurrent bulks + searches: writes
    route to the RELOCATING source, drain at the handoff barrier, and
    resume on the target — no acked write lost, no search failure, the
    recovery visible as a cancellable task with spans."""
    cluster = SimDataCluster(3, tmp_path, seed=29)
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=20)

    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    acked, rejected = [], []
    _staggered_bulks(cluster, master, acked, rejected)

    # snapshot task visibility while the recovery runs: poll /_tasks
    seen_tasks = []

    def probe_tasks():
        master.list_tasks(
            {"actions": "*recovery*", "detailed": True},
            on_done=lambda r, e=None:
            seen_tasks.extend([] if e else [
                t for n in r.get("nodes", {}).values()
                for t in n.get("tasks", {}).values()]))

    for t in (0.2, 0.6, 1.0, 1.6, 2.4):
        cluster.queue.schedule(t, probe_tasks, f"tasks-{t}")
    _move(cluster, "logs", 0, src, tgt)
    cluster.run_for(90)
    cluster.call(master.refresh)

    assert _doc_count(cluster, master) == 20 + len(acked)
    assert len(acked) > 0

    # the recovery surfaced in GET /_tasks as a cancellable task …
    rec_tasks = [t for t in seen_tasks
                 if "recovery" in t.get("action", "")]
    assert rec_tasks and all(t["cancellable"] for t in rec_tasks)
    # … and left a full span tree on the target's tracer
    tgt_cn = cluster.cluster_nodes[tgt]
    traces = tgt_cn.telemetry.tracer.recent_traces(limit=64)
    rec_traces = [t for t in traces if t["root"] == "recovery"]
    assert rec_traces and all(t["spans"] >= 3 for t in rec_traces)
    assert not [s for s in tgt_cn.telemetry.tracer.open_spans()
                if s.name.startswith("recovery")]
    (rec,) = _relocation_recs(tgt_cn)
    assert rec.task_id is not None
    # live writes arrived through tracked replication or phase-2 replay
    assert rec.translog_ops_replayed >= 0
    assert rec.stage == "done"


@pytest.mark.chaos(seed=29)
def test_relocation_under_load_is_deterministic(tmp_path):
    """Same seed ⇒ byte-identical acked set, search observations, and
    recovery telemetry (the chaos-replay contract)."""
    a = _run_relocation_under_load(tmp_path / "a", seed=29)
    b = _run_relocation_under_load(tmp_path / "b", seed=29)
    assert a == b


# ------------------------------------------------------------------ chaos

@pytest.mark.chaos(seed=11)
def test_source_death_mid_recovery_reallocates_cleanly(tmp_path):
    """Kill the source mid-phase-1: the target aborts (no half-open
    recovery), the master fails the RELOCATING source, and the shard
    re-allocates from the surviving replica — nothing stranded."""
    cluster = SimDataCluster(3, tmp_path, seed=11)
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=1)
    cluster.run_for(60)
    _index_some_docs(cluster, master, n=20)

    table = _shard_table(cluster)
    src = table.primary.current_node_id
    occupied = {s.current_node_id for s in table.shards}
    tgt = _other_data_node_id(cluster, *occupied)
    _move(cluster, "logs", 0, src, tgt)

    # let phase 1 begin, then cut the source off from everyone
    cluster.run_for(0.4)
    src_node = next(n for n in cluster.nodes if n.node_id == src)
    cluster.network.isolate(
        src_node, [n for n in cluster.nodes if n.node_id != src],
        DISCONNECTED)
    cluster.run_for(120)

    master = cluster.master()
    table = _shard_table(cluster)
    primary = table.primary
    assert primary is not None and primary.state == SHARD_STARTED
    assert primary.current_node_id != src
    assert _no_relocating_copies(cluster)
    # no abandoned recovery left live on the target
    live = [rec for rec in _relocation_recs(cluster.cluster_nodes[tgt])
            if rec.stage not in ("done", "failed", "cancelled")]
    assert not live
    # acked docs survive on the promoted copy
    coordinator = next(cn for nid, cn in cluster.cluster_nodes.items()
                       if nid != src and cn.is_master())
    assert _doc_count(cluster, coordinator) == 20


@pytest.mark.chaos(seed=43)
def test_cross_node_cancel_mid_recovery_releases_resources(tmp_path):
    """Cancel the recovery task from ANOTHER node while replay is in
    flight: the target aborts, the source's retention lease is
    released, no breaker bytes leak, and the routing table converges
    with no copy stuck RELOCATING (the ESTPU-PAIR obligation, live)."""
    cluster = SimDataCluster(3, tmp_path, seed=43)
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=40)

    src = _primary_node_id(cluster)
    tgt = _other_data_node_id(cluster, src)
    # keep writes flowing so phase 2 has ops to replay
    acked, rejected = [], []
    _staggered_bulks(cluster, master, acked, rejected, rounds=16,
                     gap=0.2)
    master.reroute(commands=[{"move": {
        "index": "logs", "shard": 0,
        "from_node": src, "to_node": tgt}}])

    # step in small slices until the target recovery is live, then
    # cancel it from a third node (cross-node cancel fan-out)
    cancelled_from = _other_data_node_id(cluster, src, tgt)
    rec = None
    for _ in range(800):
        cluster.run_for(0.02)
        live = [r for r in _relocation_recs(cluster.cluster_nodes[tgt])
                if r.stage in ("index", "translog", "device")
                and r.task_id is not None]
        if live:
            rec = live[0]
            break
    assert rec is not None, "recovery never reached a live stage"
    canceller = cluster.cluster_nodes[cancelled_from]
    cluster.call(canceller.cancel_task, f"{tgt}:{rec.task_id}",
                 reason="test cancel")
    cluster.run_for(90)

    assert rec.stage in ("cancelled", "failed", "done")
    # routing converged: an active primary, nothing stuck RELOCATING
    table = _shard_table(cluster)
    assert table.primary is not None and table.primary.active
    assert _no_relocating_copies(cluster)
    # the source released its peer-recovery retention lease
    for cn in cluster.cluster_nodes.values():
        shard = cn.data_node.shards.get(("logs", 0))
        if shard is not None and shard.tracker is not None:
            leases = shard.tracker.get_retention_leases()
            assert not [lid for lid in leases
                        if lid.startswith("peer_recovery/")], leases
    # no leaked breaker bytes or indexing-pressure charges anywhere
    for cn in cluster.cluster_nodes.values():
        svc = cn.breaker_service
        assert svc.get_breaker(
            CircuitBreaker.IN_FLIGHT_REQUESTS).used == 0
        assert cn.indexing_pressure.current_bytes() == 0
    # the cancelled task is gone from every task manager
    for cn in cluster.cluster_nodes.values():
        assert not cn.task_manager.list_tasks(actions="*recovery*")
    # writes still work after the cancel (source kept or re-won the
    # primary role) and nothing acked was lost
    cluster.call(master.refresh)
    assert _doc_count(cluster, master) == 40 + len(acked)


# ------------------------------------------------------- rolling upgrade

def test_rolling_upgrade_node_receives_relocation_over_v1(cluster):
    """A node still on wire version N-1 joins the relocation dance: the
    source detects the older peer, falls back to the single-shot v1
    recovery, and the shard serves correct searches from the old node
    (the rolling-upgrade smoke)."""
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=20)

    src = _primary_node_id(cluster)
    old = _other_data_node_id(cluster, src)
    # downgrade the target's wire version (an N-1 binary that joined)
    cluster.cluster_nodes[old].transport.wire_version = \
        CURRENT_VERSION - 1
    _move(cluster, "logs", 0, src, old)
    cluster.run_for(60)

    table = _shard_table(cluster)
    assert table.primary.current_node_id == old
    assert [s.state for s in table.shards] == [SHARD_STARTED]
    (rec,) = _relocation_recs(cluster.cluster_nodes[old])
    assert rec.protocol == CURRENT_VERSION - 1
    assert rec.stage == "done"
    assert _doc_count(cluster, master) == 20
    # and the old node can still take writes as the new primary
    resp = cluster.call(master.bulk, "logs", [
        {"op": "index", "id": f"post-{i}",
         "source": {"body": f"post upgrade {i}"}} for i in range(5)])
    assert resp["errors"] == []
    cluster.call(master.refresh)
    assert _doc_count(cluster, master) == 25


# ------------------------------------------------------- recovery API

def test_indices_recovery_fans_out_across_nodes(cluster):
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=1)
    cluster.run_for(60)
    _index_some_docs(cluster, master, n=10)
    resp = cluster.call(master.indices_recovery, "logs")
    assert "logs" in resp
    shards = resp["logs"]["shards"]
    assert shards, resp
    types = {rec["type"] for rec in shards}
    # the primary recovered from local store, the replica from a peer
    assert "local_store" in types and "peer" in types
    for rec in shards:
        assert rec["stage"] == "DONE"
        assert rec["index_files"]["recovered_bytes"] >= 0
    # filtered out for other indices
    assert cluster.call(master.indices_recovery, "nope") == {}


# ------------------------------------------- stale-state search failover

def test_shard_iterator_appends_initializing_relocation_target():
    """The relocation-flip race: a coordinator holding the pre-flip
    cluster state routes a search to the RELOCATING source, which may
    already have handed off and dropped its copy. The shard iterator
    must offer the INITIALIZING target as the next pick (ref:
    IndexShardRoutingTable.activeInitializingShardsRankedIt) so the
    retry lands on the copy that is actually started by then."""
    from elasticsearch_tpu.cluster.routing import OperationRouting
    from elasticsearch_tpu.cluster.state import (
        ClusterState, IndexRoutingTable, IndexShardRoutingTable,
        RoutingTable, ShardRouting, SHARD_INITIALIZING)

    src = ShardRouting("logs", 0, primary=True, state=SHARD_RELOCATING,
                       current_node_id="dn-0", relocating_node_id="dn-1",
                       allocation_id="a-src")
    tgt = ShardRouting("logs", 0, primary=True, state=SHARD_INITIALIZING,
                       current_node_id="dn-1", relocating_node_id="dn-0",
                       allocation_id="a-tgt")
    table = IndexShardRoutingTable("logs", 0, (src, tgt))
    state = ClusterState(routing_table=RoutingTable(
        {"logs": IndexRoutingTable("logs", {0: table})}))

    (it,) = OperationRouting().shard_iterators(state, "logs")
    first, second = it.next_or_none(), it.next_or_none()
    assert first is not None and first.allocation_id == "a-src"
    assert second is not None and second.allocation_id == "a-tgt"
    assert it.next_or_none() is None
