"""SQL line protocol + CLI (VERDICT r2 item 10; ref:
x-pack/plugin/sql/jdbc/, sql-cli): an EXTERNAL PROCESS runs SELECT with
cursor paging against a live node over the TCP protocol."""

import os
import subprocess
import sys

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.xpack.sql_protocol import SqlClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def node(tmp_path):
    n = Node(settings=Settings.from_dict({
        "xpack": {"sql": {"port": 0}},
    }), data_path=str(tmp_path / "data"))
    n.start(0)
    c = n.rest_controller
    status, _ = c.dispatch("PUT", "/emp", {}, {
        "mappings": {"properties": {
            "name": {"type": "keyword"},
            "salary": {"type": "integer"},
            "dept": {"type": "keyword"}}}})
    assert status == 200
    for i in range(25):
        status, _ = c.dispatch("PUT", f"/emp/_doc/{i}", {}, {
            "name": f"emp{i:02d}", "salary": 1000 + i * 10,
            "dept": "eng" if i % 2 == 0 else "ops"})
        assert status == 201
    c.dispatch("POST", "/emp/_refresh", {}, None)
    yield n
    n.close()


def test_protocol_select_with_cursor_paging(node):
    client = SqlClient(port=node._sql_protocol.port)
    try:
        pages = list(client.query(
            "SELECT name, salary FROM emp ORDER BY salary DESC",
            fetch_size=10))
        assert len(pages) >= 3                 # 25 rows / 10 per page
        cols = pages[0][0]
        assert [c["name"] for c in cols] == ["name", "salary"]
        rows = [r for _, page in pages for r in page]
        assert len(rows) == 25
        assert rows[0] == ["emp24", 1240]
        salaries = [r[1] for r in rows]
        assert salaries == sorted(salaries, reverse=True)
    finally:
        client.close()


def test_protocol_aggregation_and_errors(node):
    client = SqlClient(port=node._sql_protocol.port)
    try:
        pages = list(client.query(
            "SELECT dept, COUNT(*) AS n, MAX(salary) AS top FROM emp "
            "GROUP BY dept ORDER BY dept"))
        rows = [r for _, page in pages for r in page]
        assert rows == [["eng", 13, 1240], ["ops", 12, 1230]]
        with pytest.raises(RuntimeError, match="(?i)parsing|expected|syntax"):
            list(client.query("SELEC broken"))
    finally:
        client.close()


def test_external_process_cli(node):
    """The CLI binary in a SEPARATE process pages a SELECT via the
    protocol (the done-condition of VERDICT item 10)."""
    out = subprocess.run(
        [sys.executable, "-m", "elasticsearch_tpu.xpack.sql_protocol",
         "--port", str(node._sql_protocol.port), "--fetch-size", "7",
         "-e", "SELECT name FROM emp WHERE salary >= 1200 "
               "ORDER BY name"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT,
             "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "emp20" in out.stdout and "emp24" in out.stdout
    assert "(5 rows)" in out.stdout
    # error path exits non-zero
    bad = subprocess.run(
        [sys.executable, "-m", "elasticsearch_tpu.xpack.sql_protocol",
         "--port", str(node._sql_protocol.port), "-e", "NOT SQL"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT,
             "JAX_PLATFORMS": "cpu"})
    assert bad.returncode == 1
    assert "ERROR" in bad.stderr


def test_protocol_enforces_security(tmp_path):
    """With x-pack security enabled the SQL port demands credentials and
    runs the realm chain + the REST /_sql privilege check — the
    protocol is never an authz bypass."""
    n = Node(settings=Settings.from_dict({
        "xpack": {"sql": {"port": 0},
                  "security": {"enabled": True}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))
    n.start(0)
    try:
        c = n.rest_controller
        import base64
        auth = {"Authorization": "Basic " + base64.b64encode(
            b"elastic:s3cret").decode()}
        status, _ = c.dispatch("PUT", "/t/_doc/1", {}, {"v": 1},
                               headers=auth)
        assert status == 201
        c.dispatch("POST", "/t/_refresh", {}, None, headers=auth)
        port = n._sql_protocol.port
        # no credentials → authentication error
        anon = SqlClient(port=port)
        with pytest.raises(RuntimeError, match="(?i)authent|credent"):
            list(anon.query("SELECT v FROM t"))
        anon.close()
        # wrong password → refused
        bad = SqlClient(port=port, username="elastic", password="nope")
        with pytest.raises(RuntimeError, match="(?i)authent|credent"):
            list(bad.query("SELECT v FROM t"))
        bad.close()
        # valid credentials → rows
        ok = SqlClient(port=port, username="elastic",
                       password="s3cret")
        pages = list(ok.query("SELECT v FROM t"))
        assert [r for _, p in pages for r in p] == [[1]]
        ok.close()
    finally:
        n.close()
