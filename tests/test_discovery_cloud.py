"""discovery-gce and discovery-azure-classic seed providers (ref:
plugins/discovery-gce/.../GceSeedHostsProvider.java,
plugins/discovery-azure-classic/.../AzureSeedHostsProvider.java)
against in-process fixtures verifying the real request shapes: the GCE
metadata-server token dance + Bearer-authorized Compute API list, and
the Azure Service Management XML with its x-ms-version header."""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from elasticsearch_tpu.cluster import discovery
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.plugins import PluginsService
from elasticsearch_tpu.plugins import main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GCE_INSTANCES = {
    "items": [
        {"name": "es-1", "status": "RUNNING",
         "tags": {"items": ["elasticsearch", "dev"]},
         "networkInterfaces": [{"networkIP": "10.240.0.2",
                                "accessConfigs": [{"natIP": "35.1.1.1"}]}]},
        {"name": "es-2", "status": "RUNNING",
         "tags": {"items": ["elasticsearch"]},
         "networkInterfaces": [{"networkIP": "10.240.0.3"}]},
        {"name": "db-1", "status": "RUNNING",
         "tags": {"items": ["postgres"]},
         "networkInterfaces": [{"networkIP": "10.240.0.9"}]},
        {"name": "es-stopped", "status": "TERMINATED",
         "tags": {"items": ["elasticsearch"]},
         "networkInterfaces": [{"networkIP": "10.240.0.4"}]},
    ]
}

AZURE_XML = """<?xml version="1.0" encoding="utf-8"?>
<HostedService xmlns="http://schemas.microsoft.com/windowsazure">
 <Deployments>
  <Deployment>
   <Name>prod-deploy</Name>
   <DeploymentSlot>Production</DeploymentSlot>
   <RoleInstanceList>
    <RoleInstance>
     <InstanceName>es-0</InstanceName>
     <IpAddress>10.0.0.4</IpAddress>
     <InstanceEndpoints>
      <InstanceEndpoint><Name>elasticsearch</Name>
       <Vip>104.40.1.1</Vip><PublicPort>9301</PublicPort>
      </InstanceEndpoint>
     </InstanceEndpoints>
    </RoleInstance>
    <RoleInstance>
     <InstanceName>es-1</InstanceName>
     <IpAddress>10.0.0.5</IpAddress>
     <InstanceEndpoints>
      <InstanceEndpoint><Name>elasticsearch</Name>
       <Vip>104.40.1.2</Vip><PublicPort>9302</PublicPort>
      </InstanceEndpoint>
     </InstanceEndpoints>
    </RoleInstance>
   </RoleInstanceList>
  </Deployment>
  <Deployment>
   <Name>staging-deploy</Name>
   <DeploymentSlot>Staging</DeploymentSlot>
   <RoleInstanceList>
    <RoleInstance>
     <InstanceName>es-stg</InstanceName>
     <IpAddress>10.9.0.1</IpAddress>
    </RoleInstance>
   </RoleInstanceList>
  </Deployment>
 </Deployments>
</HostedService>"""


class _CloudFixture(BaseHTTPRequestHandler):
    requests = []

    def log_message(self, *a):
        pass

    def do_GET(self):
        _CloudFixture.requests.append((self.path, dict(self.headers)))
        if self.path.endswith("/token"):
            if self.headers.get("Metadata-Flavor") != "Google":
                self._send(403, b"{}")
                return
            self._send(200, json.dumps(
                {"access_token": "gce-tok-123",
                 "token_type": "Bearer", "expires_in": 3600}).encode())
        elif "/zones/" in self.path:
            if self.headers.get("Authorization") != "Bearer gce-tok-123":
                self._send(401, b"{}")
                return
            self._send(200, json.dumps(GCE_INSTANCES).encode())
        elif "/services/hostedservices/" in self.path:
            if not self.headers.get("x-ms-version"):
                self._send(400, b"missing x-ms-version")
                return
            self._send(200, AZURE_XML.encode())
        else:
            self._send(404, b"")

    def _send(self, status, body):
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def fixture():
    srv = HTTPServer(("127.0.0.1", 0), _CloudFixture)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    _CloudFixture.requests.clear()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


@pytest.fixture()
def providers(tmp_path):
    pd = str(tmp_path / "plugins")
    for name in ("discovery_gce", "discovery_azure_classic"):
        plugin_cli(["install", os.path.join(REPO_ROOT, "plugins_src", name),
                    "--plugins-dir", pd])
    svc = PluginsService(pd)
    svc.load_all()
    yield svc
    discovery.PLUGIN_SEED_PROVIDERS.pop("gce", None)
    discovery.PLUGIN_SEED_PROVIDERS.pop("azure", None)


def test_gce_seed_hosts_tag_filter_and_auth(fixture, providers):
    settings = Settings.from_dict({
        "cloud": {"gce": {"project_id": "proj-1", "zone": "us-central1-a",
                          "metadata": {"endpoint": fixture}}},
        "discovery": {"gce": {"endpoint": fixture,
                              "tags": "elasticsearch",
                              "port": 9344}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    # RUNNING + tagged instances only; the stopped and postgres ones drop
    assert [(n.host, n.port) for n in seeds] == [
        ("10.240.0.2", 9344), ("10.240.0.3", 9344)]
    paths = [p for p, _ in _CloudFixture.requests]
    assert any(p.endswith("/service-accounts/default/token")
               for p in paths)
    assert any("/projects/proj-1/zones/us-central1-a/instances" in p
               for p in paths)
    # metadata request carried the required header
    tok_hdrs = next(h for p, h in _CloudFixture.requests
                    if p.endswith("/token"))
    assert tok_hdrs.get("Metadata-Flavor") == "Google"


def test_gce_multi_zone_and_unreachable(fixture, providers):
    settings = Settings.from_dict({
        "cloud": {"gce": {"project_id": "proj-1",
                          "zone": "us-central1-a,europe-west1-b",
                          "metadata": {"endpoint": fixture}}},
        "discovery": {"gce": {"endpoint": fixture}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    # no tag filter: all three RUNNING instances; the fixture serves the
    # same instance list for both zones, so dedup leaves one of each
    assert len(seeds) == 3
    paths = [p for p, _ in _CloudFixture.requests]
    assert any("/zones/us-central1-a/instances" in p for p in paths)
    assert any("/zones/europe-west1-b/instances" in p for p in paths)
    bad = Settings.from_dict({
        "cloud": {"gce": {"project_id": "p", "zone": "z",
                          "metadata": {"endpoint":
                                       "http://127.0.0.1:1"}}},
        "discovery": {"gce": {"endpoint": "http://127.0.0.1:1"}}})
    assert discovery.resolve_seed_hosts(settings=bad) == []


def test_azure_private_ip_production_slot(fixture, providers):
    settings = Settings.from_dict({
        "cloud": {"azure": {"management": {
            "subscription": {"id": "sub-123"},
            "cloud": {"service": {"name": "my-es"}}}}},
        "discovery": {"azure": {"endpoint": fixture}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    # production deployment only; staging's 10.9.0.1 filtered by slot
    assert [(n.host, n.port) for n in seeds] == [
        ("10.0.0.4", 9300), ("10.0.0.5", 9300)]
    path, headers = next((p, h) for p, h in _CloudFixture.requests
                         if "hostedservices" in p)
    assert "/sub-123/services/hostedservices/my-es" in path
    assert "embed-detail=true" in path
    assert {k.lower(): v for k, v in headers.items()}.get(
        "x-ms-version") == "2014-10-01"


def test_azure_public_ip_endpoint_and_slot_filter(fixture, providers):
    settings = Settings.from_dict({
        "cloud": {"azure": {"management": {
            "subscription": {"id": "sub-123"},
            "cloud": {"service": {"name": "my-es"}}}}},
        "discovery": {"azure": {"endpoint": fixture,
                                "host": {"type": "public_ip"}}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    # Vip + PublicPort of the 'elasticsearch' instance endpoint
    assert [(n.host, n.port) for n in seeds] == [
        ("104.40.1.1", 9301), ("104.40.1.2", 9302)]
    staging = Settings.from_dict({
        "cloud": {"azure": {"management": {
            "subscription": {"id": "sub-123"},
            "cloud": {"service": {"name": "my-es"}}}}},
        "discovery": {"azure": {"endpoint": fixture,
                                "deployment": {"slot": "staging"}}}})
    seeds = discovery.resolve_seed_hosts(settings=staging)
    assert [(n.host, n.port) for n in seeds] == [("10.9.0.1", 9300)]


def test_both_merge_with_static_seeds(fixture, providers):
    settings = Settings.from_dict({
        "discovery": {
            "seed_hosts": "192.168.7.7:9300",
            "gce": {"endpoint": fixture, "tags": "elasticsearch"},
            "azure": {"endpoint": fixture}},
        "cloud": {
            "gce": {"project_id": "proj-1", "zone": "us-central1-a",
                    "metadata": {"endpoint": fixture}},
            "azure": {"management": {
                "subscription": {"id": "sub-123"},
                "cloud": {"service": {"name": "my-es"}}}}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    hosts = [n.host for n in seeds]
    assert "192.168.7.7" in hosts          # static
    assert "10.240.0.2" in hosts           # gce
    assert "10.0.0.4" in hosts             # azure
