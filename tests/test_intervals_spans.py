"""Intervals + span query family tests (model: the reference's
IntervalQueryBuilder/SpanNearQueryBuilder test coverage), plus
terms_set / script / wrapper queries."""

import base64
import json

import pytest

from elasticsearch_tpu.index.service import IndicesService
from elasticsearch_tpu.search.service import SearchService


@pytest.fixture(scope="module")
def search(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("iv")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("d", {}, {"properties": {
        "t": {"type": "text"},
        "tags": {"type": "keyword"},
        "required_matches": {"type": "long"},
        "n": {"type": "long"}}})
    docs = {
        "1": {"t": "the cold war ended quietly", "tags": ["a", "b"],
              "required_matches": 2, "n": 5},
        "2": {"t": "cold winter war stories", "tags": ["a"],
              "required_matches": 1, "n": 10},
        "3": {"t": "war never changes in the cold", "tags": ["b", "c"],
              "required_matches": 3, "n": 15},
        "4": {"t": "warm summer days", "tags": ["c"],
              "required_matches": 1, "n": 20},
    }
    for did, d in docs.items():
        idx.index_doc(did, d)
    idx.refresh()
    yield SearchService(indices)
    indices.close()


def ids(r):
    return sorted(h["_id"] for h in r["hits"]["hits"])


def test_intervals_match_ordered(search):
    r = search.search("d", {"query": {"intervals": {"t": {
        "match": {"query": "cold war", "ordered": True,
                  "max_gaps": 0}}}}})
    assert ids(r) == ["1"]                  # only doc1 has them adjacent


def test_intervals_match_unordered_gaps(search):
    r = search.search("d", {"query": {"intervals": {"t": {
        "match": {"query": "cold war", "ordered": False,
                  "max_gaps": 1}}}}})
    # doc1 adjacent; doc2 has one word between; doc3 gap of 4
    assert ids(r) == ["1", "2"]


def test_intervals_any_of(search):
    r = search.search("d", {"query": {"intervals": {"t": {
        "any_of": {"intervals": [
            {"match": {"query": "winter"}},
            {"match": {"query": "summer"}}]}}}}})
    assert ids(r) == ["2", "4"]


def test_intervals_all_of_ordered(search):
    r = search.search("d", {"query": {"intervals": {"t": {
        "all_of": {"ordered": True, "intervals": [
            {"match": {"query": "war"}},
            {"match": {"query": "cold"}}]}}}}})
    assert ids(r) == ["3"]                  # war ... cold in order


def test_span_near(search):
    r = search.search("d", {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "cold"}},
                    {"span_term": {"t": "war"}}],
        "slop": 1, "in_order": True}}})
    assert ids(r) == ["1", "2"]


def test_span_or_and_first(search):
    r = search.search("d", {"query": {"span_or": {"clauses": [
        {"span_term": {"t": "winter"}},
        {"span_term": {"t": "summer"}}]}}})
    assert ids(r) == ["2", "4"]
    # span_first: "war" within the first 2 positions
    r = search.search("d", {"query": {"span_first": {
        "match": {"span_term": {"t": "war"}}, "end": 2}}})
    assert ids(r) == ["3"]                  # war at position 0 only in doc3


def test_span_not(search):
    # "cold" not followed/preceded by overlapping "winter cold"... use
    # include=cold, exclude=cold war (ordered adjacent)
    r = search.search("d", {"query": {"span_not": {
        "include": {"span_term": {"t": "cold"}},
        "exclude": {"span_near": {
            "clauses": [{"span_term": {"t": "cold"}},
                        {"span_term": {"t": "war"}}],
            "slop": 0, "in_order": True}}}}})
    # doc1's cold is part of "cold war" → excluded; docs 2,3 keep a cold
    assert ids(r) == ["2", "3"]


def test_terms_set_field(search):
    r = search.search("d", {"query": {"terms_set": {"tags": {
        "terms": ["a", "b", "c"],
        "minimum_should_match_field": "required_matches"}}}})
    # doc1 needs 2, has a+b → match; doc2 needs 1, has a → match;
    # doc3 needs 3, has b+c → no; doc4 needs 1, has c → match
    assert ids(r) == ["1", "2", "4"]


def test_terms_set_script(search):
    r = search.search("d", {"query": {"terms_set": {"tags": {
        "terms": ["a", "b"],
        "minimum_should_match_script": {
            "source": "Math.min(params.num_terms, 2)"}}}}})
    assert ids(r) == ["1"]                  # only doc1 has both a and b


def test_script_query(search):
    r = search.search("d", {"query": {"script": {"script": {
        "source": "doc['n'].value > 12"}}}})
    assert ids(r) == ["3", "4"]


def test_wrapper_query(search):
    inner = {"term": {"tags": {"value": "c"}}}
    encoded = base64.b64encode(json.dumps(inner).encode()).decode()
    r = search.search("d", {"query": {"wrapper": {"query": encoded}}})
    assert ids(r) == ["3", "4"]


def test_intervals_empty_match_under_any_of(search):
    # an empty match leg must contribute nothing, not crash
    r = search.search("d", {"query": {"intervals": {"t": {
        "any_of": {"intervals": [
            {"match": {"query": ""}},
            {"match": {"query": "winter"}}]}}}}})
    assert ids(r) == ["2"]


def test_terms_set_msm_script_forms(search):
    # params.num_terms form requires all terms
    r = search.search("d", {"query": {"terms_set": {"tags": {
        "terms": ["a", "b"],
        "minimum_should_match_script": {"source": "params.num_terms"}}}}})
    assert ids(r) == ["1"]
    # constant form
    r = search.search("d", {"query": {"terms_set": {"tags": {
        "terms": ["a", "b", "c"],
        "minimum_should_match_script": {"source": "1"}}}}})
    assert ids(r) == ["1", "2", "3", "4"]
    # interpreter-escape attempts are never evaluated: unknown scripts
    # fall back to requiring all terms
    r = search.search("d", {"query": {"terms_set": {"tags": {
        "terms": ["a", "b"],
        "minimum_should_match_script": {
            "source": "().__class__ and params.num_terms"}}}}})
    assert ids(r) == ["1"]


def test_span_containing_field_mismatch_rejected(search):
    from elasticsearch_tpu.common.errors import ParsingException
    with pytest.raises(ParsingException):
        search.search("d", {"query": {"span_containing": {
            "big": {"span_term": {"t": "war"}},
            "little": {"span_term": {"tags": "a"}}}}})


def test_intervals_boost_applies(search):
    r1 = search.search("d", {"query": {"intervals": {"t": {
        "match": {"query": "winter"}}}}})
    r2 = search.search("d", {"query": {"intervals": {"t": {
        "match": {"query": "winter"}, "boost": 3.0}}}})
    assert r2["hits"]["hits"][0]["_score"] == pytest.approx(
        3.0 * r1["hits"]["hits"][0]["_score"])


@pytest.fixture(scope="module")
def nested_search(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("nested")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("orders", {}, {"properties": {
        "order": {"type": "keyword"},
        "items": {"type": "nested", "properties": {
            "product": {"type": "keyword"},
            "qty": {"type": "long"}}}}})
    idx.index_doc("1", {"order": "a", "items": [
        {"product": "widget", "qty": 10},
        {"product": "gadget", "qty": 1}]})
    # cross-object combination: widget qty=1 + gadget qty=10 — flattened
    # matching would wrongly match (widget AND qty>=5 across objects)
    idx.index_doc("2", {"order": "b", "items": [
        {"product": "widget", "qty": 1},
        {"product": "gadget", "qty": 10}]})
    idx.refresh()
    yield SearchService(indices)
    indices.close()


def test_nested_query_per_object_correlation(nested_search):
    r = nested_search.search("orders", {"query": {"nested": {
        "path": "items",
        "query": {"bool": {"must": [
            {"term": {"items.product": {"value": "widget"}}},
            {"range": {"items.qty": {"gte": 5}}}]}}}}})
    # only doc1 has ONE object with product=widget AND qty>=5
    assert ids(r) == ["1"]


def test_nested_query_simple_term(nested_search):
    r = nested_search.search("orders", {"query": {"nested": {
        "path": "items",
        "query": {"term": {"items.product": {"value": "gadget"}}}}}})
    assert ids(r) == ["1", "2"]


def test_nested_unmapped_path(nested_search):
    from elasticsearch_tpu.common.errors import QueryShardException
    with pytest.raises(QueryShardException):
        nested_search.search("orders", {"query": {"nested": {
            "path": "nope", "query": {"match_all": {}}}}})
    r = nested_search.search("orders", {"query": {"nested": {
        "path": "nope", "query": {"match_all": {}},
        "ignore_unmapped": True}}})
    assert r["hits"]["total"]["value"] == 0


def test_nested_mapping_roundtrip(nested_search):
    idx = nested_search.indices_service.get("orders")
    m = idx.mapper.to_mapping()
    assert m["properties"]["items"]["type"] == "nested"


def test_nested_verifier_edge_cases(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("nested2")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("n2", {}, {"properties": {
        "a": {"type": "nested", "properties": {
            "b": {"type": "nested", "properties": {
                "v": {"type": "keyword"}}}}},
        "items": {"type": "nested", "properties": {
            "note": {"type": "text"},
            "qty": {"type": "long"}}}}})
    idx.index_doc("1", {"a": [{"b": [{"v": "x"}]}],
                        "items": [{"note": "Fast delivery!",
                                   "qty": "7"}]})
    idx.refresh()
    svc = SearchService(indices)
    # nested-under-nested paths traverse lists mid-path
    r = svc.search("n2", {"query": {"nested": {
        "path": "a.b", "query": {"term": {"a.b.v": {"value": "x"}}}}}})
    assert ids(r) == ["1"]
    # single-clause bool shorthand
    r = svc.search("n2", {"query": {"nested": {
        "path": "a.b",
        "query": {"bool": {"must": {"term": {"a.b.v": {"value": "x"}}}}}}}})
    assert ids(r) == ["1"]
    # match verification analyzes with the field analyzer (punctuation)
    r = svc.search("n2", {"query": {"nested": {
        "path": "items",
        "query": {"match": {"items.note": {"query": "delivery"}}}}}})
    assert ids(r) == ["1"]
    # range verification coerces through the field type ("7" >= 5)
    r = svc.search("n2", {"query": {"nested": {
        "path": "items",
        "query": {"range": {"items.qty": {"gte": 5}}}}}})
    assert ids(r) == ["1"]
    indices.close()


def test_nested_inner_hits(nested_search):
    r = nested_search.search("orders", {"query": {"nested": {
        "path": "items",
        "query": {"term": {"items.product": {"value": "gadget"}}},
        "inner_hits": {}}}})
    hit = next(h for h in r["hits"]["hits"] if h["_id"] == "1")
    ih = hit["inner_hits"]["items"]["hits"]
    assert ih["total"]["value"] == 1
    assert ih["hits"][0]["_source"]["product"] == "gadget"
    assert ih["hits"][0]["_nested"] == {"field": "items", "offset": 1}


def test_span_multi_prefix(search):
    r = search.search("d", {"query": {"span_multi": {
        "match": {"prefix": {"t": {"value": "wa"}}}}}})
    # matches docs containing war/warm
    assert ids(r) == ["1", "2", "3", "4"]
    r = search.search("d", {"query": {"span_near": {
        "clauses": [{"span_term": {"t": "cold"}},
                    {"span_multi": {"match": {
                        "prefix": {"t": {"value": "wa"}}}}}],
        "slop": 0, "in_order": True}}})
    assert ids(r) == ["1"]                  # cold war adjacent


def test_span_multi_wildcard_full_pattern(search):
    # full wildcard semantics: w?r matches war but NOT warm
    r = search.search("d", {"query": {"span_multi": {
        "match": {"wildcard": {"t": {"value": "w?r"}}}}}})
    assert ids(r) == ["1", "2", "3"]        # docs with "war", not doc4
    # malformed bodies parse-error (400), not internal errors
    from elasticsearch_tpu.common.errors import ParsingException
    with pytest.raises(ParsingException):
        search.search("d", {"query": {"span_multi": {}}})


def test_field_masking_span(tmp_path_factory):
    """ref: index/query/FieldMaskingSpanQueryBuilder — spans from one
    field combine with another field's spans inside span_near (the
    same-content-different-analysis pattern)."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("fms")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("m", {}, {"properties": {
        "t": {"type": "text"},
        "t_exact": {"type": "text", "analyzer": "whitespace"}}})
    docs = [
        ("1", "The Quick brown fox"),
        ("2", "slow Quick turtle"),
        ("3", "brown bear Quick"),
    ]
    for did, text in docs:
        idx.index_doc(did, {"t": text, "t_exact": text})
    idx.refresh()
    svc = SearchService(indices)
    # 'Quick' survives only in the whitespace field (unlowercased);
    # masking lets span_near chain it before the standard field's
    # 'brown' — only doc 1 has Quick immediately before brown
    r = svc.search("m", {"query": {"span_near": {
        "clauses": [
            {"field_masking_span": {
                "query": {"span_term": {"t_exact": "Quick"}},
                "field": "t"}},
            {"span_term": {"t": "brown"}}],
        "slop": 0, "in_order": True}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    # standalone masked span matches where the source field matches
    r = svc.search("m", {"query": {"field_masking_span": {
        "query": {"span_term": {"t_exact": "Quick"}},
        "field": "t"}}})
    assert sorted(h["_id"] for h in r["hits"]["hits"]) == ["1", "2", "3"]
    # order still binds across the mask: brown BEFORE the masked Quick
    # only holds in doc 3
    r = svc.search("m", {"query": {"span_near": {
        "clauses": [
            {"span_term": {"t": "brown"}},
            {"field_masking_span": {
                "query": {"span_term": {"t_exact": "Quick"}},
                "field": "t"}}],
        "slop": 1, "in_order": True}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["3"]
    indices.close()


def test_field_masking_span_in_filter_position(tmp_path_factory):
    """Masked subtrees inside span_not's exclude (filter position) read
    their own field's token row."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("fmsf")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("m", {}, {"properties": {
        "t": {"type": "text"},
        "t_exact": {"type": "text", "analyzer": "whitespace"}}})
    for did, text in (("1", "The Quick brown fox"),
                      ("2", "slow brown turtle")):
        idx.index_doc(did, {"t": text, "t_exact": text})
    idx.refresh()
    svc = SearchService(indices)
    r = svc.search("m", {"query": {"span_not": {
        "include": {"span_term": {"t": "brown"}},
        "exclude": {"span_near": {"clauses": [
            {"field_masking_span": {
                "query": {"span_term": {"t_exact": "Quick"}},
                "field": "t"}},
            {"span_term": {"t": "brown"}}],
            "slop": 0, "in_order": True}}}}})
    # doc 1's brown is adjacent to the masked Quick → excluded
    assert [h["_id"] for h in r["hits"]["hits"]] == ["2"]
    indices.close()
