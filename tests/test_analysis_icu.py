"""analysis-icu plugin tests (ref: plugins/analysis-icu test suite:
normalization, folding, Unicode/CJK tokenization — driven through the
installed plugin over REST)."""

import os

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def node(tmp_path):
    pd = str(tmp_path / "plugins")
    plugin_cli(["install",
                os.path.join(REPO_ROOT, "plugins_src", "analysis_icu"),
                "--plugins-dir", pd])
    n = Node(settings=Settings.from_dict({"path": {"plugins": pd}}),
             data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, expect=200):
    status, r = node.rest_controller.dispatch(method, path, None, body)
    assert status == expect, r
    return r


def terms(node, index, analyzer, text):
    r = call(node, "GET", f"/{index}/_analyze",
             {"analyzer": analyzer, "text": text})
    return [t["token"] for t in r["tokens"]]


@pytest.fixture()
def idx(node):
    call(node, "PUT", "/icu", {
        "settings": {"analysis": {
            "filter": {
                "norm": {"type": "icu_normalizer"},
                "foldit": {"type": "icu_folding"},
            },
            "analyzer": {
                "icu_norm": {"type": "custom", "tokenizer": "standard",
                             "filter": ["norm"]},
                "icu_fold": {"type": "custom", "tokenizer": "standard",
                             "filter": ["foldit"]},
                "icu_words": {"type": "custom",
                              "tokenizer": "icu_tokenizer",
                              "filter": ["norm"]},
            }}},
        "mappings": {"properties": {
            "t": {"type": "text", "analyzer": "icu_fold"}}}})
    return node


def test_icu_normalizer(idx):
    # NFKC + casefold: width folding, compatibility forms, case
    assert terms(idx, "icu", "icu_norm", "ＦＵＬＬｗｉｄｔｈ") == ["fullwidth"]
    assert terms(idx, "icu", "icu_norm", "ﬁopenoﬃce") == ["fiopenoffice"]
    assert terms(idx, "icu", "icu_norm", "Straße") == ["strasse"]


def test_icu_folding(idx):
    assert terms(idx, "icu", "icu_fold", "Café Ågård naïve") == \
        ["cafe", "agard", "naive"]
    assert terms(idx, "icu", "icu_fold", "Ελληνικά") == ["ελληνικα"]


def test_icu_tokenizer_cjk(idx):
    # Han characters segment one-per-token (dictionary-less ICU), Latin
    # words stay whole
    assert terms(idx, "icu", "icu_words", "ток 東京都 tower") == \
        ["ток", "東", "京", "都", "tower"]


def test_folded_search_matches(idx):
    call(idx, "PUT", "/icu/_doc/1", {"t": "Crème Brûlée"}, expect=201)
    call(idx, "POST", "/icu/_refresh")
    r = call(idx, "POST", "/icu/_search",
             {"query": {"match": {"t": "creme brulee"}}})
    assert r["hits"]["total"]["value"] == 1
