"""Engine/translog/seqno tests (model: the reference's InternalEngineTests,
TranslogTests, LocalCheckpointTrackerTests)."""

import json
import os

import pytest

from elasticsearch_tpu.common.errors import (
    TranslogCorruptedException,
    VersionConflictEngineException,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker, ReplicationTracker
from elasticsearch_tpu.index.translog import Translog, TranslogOp

MAPPINGS = {"properties": {"body": {"type": "text"}, "n": {"type": "long"}}}


@pytest.fixture
def engine(tmp_path):
    e = Engine(str(tmp_path / "shard0"), MapperService(mappings=MAPPINGS))
    yield e
    e.close()


# --------------------------------------------------------------- translog

def test_translog_roundtrip(tmp_path):
    t = Translog(str(tmp_path / "tl"))
    t.add(TranslogOp("index", 0, 1, doc_id="a", source={"x": 1}))
    t.add(TranslogOp("delete", 1, 1, doc_id="a"))
    t.sync()
    ops = t.read_ops()
    assert [o.op_type for o in ops] == ["index", "delete"]
    assert ops[0].source == {"x": 1}
    t.close()


def test_translog_survives_reopen(tmp_path):
    p = str(tmp_path / "tl")
    t = Translog(p)
    t.add(TranslogOp("index", 0, 1, doc_id="a", source={}))
    t.sync()
    t.close()
    t2 = Translog(p)
    assert len(t2.read_ops()) == 1
    t2.add(TranslogOp("index", 1, 1, doc_id="b", source={}))
    assert len(t2.read_ops()) == 2
    t2.close()


def test_translog_torn_tail_truncated(tmp_path):
    p = str(tmp_path / "tl")
    t = Translog(p)
    t.add(TranslogOp("index", 0, 1, doc_id="a", source={}))
    t.sync()
    t.close()
    # simulate a torn write: append garbage half-record
    with open(os.path.join(p, "translog-1.log"), "ab") as fh:
        fh.write(b"\x50\x00\x00\x00partial")
    t2 = Translog(p)
    assert len(t2.read_ops()) == 1  # torn tail dropped
    t2.close()


def test_translog_detects_corruption(tmp_path):
    p = str(tmp_path / "tl")
    t = Translog(p)
    t.add(TranslogOp("index", 0, 1, doc_id="a", source={"k": "v"}))
    t.sync()
    t.close()
    path = os.path.join(p, "translog-1.log")
    data = bytearray(open(path, "rb").read())
    data[10] ^= 0xFF  # flip a payload byte -> crc mismatch
    open(path, "wb").write(bytes(data))
    # surfaces at reopen (counter restore reads the log) — never silently
    with pytest.raises(TranslogCorruptedException):
        Translog(p)


def test_translog_generation_roll_and_trim(tmp_path):
    p = str(tmp_path / "tl")
    t = Translog(p)
    t.add(TranslogOp("index", 0, 1, doc_id="a", source={}))
    gen = t.roll_generation()
    t.add(TranslogOp("index", 1, 1, doc_id="b", source={}))
    assert len(t.read_ops()) == 2
    assert len(t.read_ops(from_generation=gen)) == 1
    t.trim_generations(gen)
    assert not os.path.exists(os.path.join(p, "translog-1.log"))
    t.close()


# ----------------------------------------------------------------- seqno

def test_local_checkpoint_contiguous():
    t = LocalCheckpointTracker()
    s0, s1, s2 = t.generate_seq_no(), t.generate_seq_no(), t.generate_seq_no()
    t.mark_seq_no_as_processed(s0)
    t.mark_seq_no_as_processed(s2)  # gap at s1
    assert t.checkpoint == 0
    t.mark_seq_no_as_processed(s1)
    assert t.checkpoint == 2
    assert t.max_seq_no == 2


def test_replication_tracker_global_checkpoint():
    rt = ReplicationTracker("primary", local_checkpoint=5)
    assert rt.global_checkpoint == 5
    rt.init_tracking("replica1")
    rt.mark_in_sync("replica1", 3)
    # replica behind: global checkpoint can't go backwards but min is 3 — it
    # stays at 5 only if already advanced; fresh min over {5,3} is 3 -> no
    # regression allowed
    assert rt.global_checkpoint == 5
    rt.update_local_checkpoint("replica1", 7)
    rt.update_local_checkpoint("primary", 9)
    assert rt.global_checkpoint == 7
    rt.remove_copy("replica1")
    assert rt.global_checkpoint == 9


def test_retention_leases():
    rt = ReplicationTracker("p", local_checkpoint=10)
    rt.add_retention_lease("peer_recovery/r1", 4, "peer recovery")
    assert rt.min_retained_seq_no() == 4
    rt.renew_retention_lease("peer_recovery/r1", 8)
    assert rt.min_retained_seq_no() == 8
    rt.remove_retention_lease("peer_recovery/r1")
    assert rt.min_retained_seq_no() == 11


# ---------------------------------------------------------------- engine

def test_index_get_realtime(engine):
    r = engine.index("1", {"body": "hello world", "n": 1})
    assert r.created and r.version == 1 and r.seq_no == 0
    g = engine.get("1")  # before any refresh
    assert g.found and g.source == {"body": "hello world", "n": 1}


def test_update_increments_version(engine):
    engine.index("1", {"n": 1})
    r2 = engine.index("1", {"n": 2})
    assert not r2.created and r2.version == 2
    assert engine.get("1").source == {"n": 2}
    engine.refresh()
    assert engine.get("1").source == {"n": 2}
    assert engine.stats()["docs"]["count"] == 1


def test_update_after_refresh_tombstones_old(engine):
    engine.index("1", {"n": 1})
    engine.refresh()
    engine.index("1", {"n": 2})
    engine.refresh()
    assert engine.stats()["docs"]["count"] == 1
    assert engine.get("1").source == {"n": 2}
    snap = engine.acquire_searcher()
    live = sum(s.live_doc_count for s in snap.segments)
    assert live == 1


def test_delete(engine):
    engine.index("1", {"n": 1})
    d = engine.delete("1")
    assert d.found and d.version == 2
    assert not engine.get("1").found
    d2 = engine.delete("nope")
    assert not d2.found


def test_create_conflict(engine):
    engine.index("1", {"n": 1})
    with pytest.raises(VersionConflictEngineException):
        engine.index("1", {"n": 2}, op_type="create")


def test_cas_if_seq_no(engine):
    r = engine.index("1", {"n": 1})
    r2 = engine.index("1", {"n": 2}, if_seq_no=r.seq_no, if_primary_term=r.primary_term)
    assert r2.version == 2
    with pytest.raises(VersionConflictEngineException):
        engine.index("1", {"n": 3}, if_seq_no=r.seq_no, if_primary_term=r.primary_term)


def test_refresh_publishes_segment(engine):
    engine.index("1", {"body": "x"})
    snap0 = engine.acquire_searcher()
    assert snap0.doc_count == 0  # not yet visible to search
    assert engine.refresh() is True
    snap1 = engine.acquire_searcher()
    assert snap1.doc_count == 1
    assert snap1.epoch > snap0.epoch
    assert engine.refresh() is False  # empty buffer


def test_flush_and_recover(tmp_path):
    path = str(tmp_path / "shardX")
    e = Engine(path, MapperService(mappings=MAPPINGS))
    e.index("1", {"body": "persisted doc", "n": 1})
    e.index("2", {"body": "second", "n": 2})
    e.flush()
    e.index("3", {"body": "only in translog", "n": 3})
    e.translog.sync()
    e.close()

    e2 = Engine(path, MapperService(mappings=MAPPINGS))
    assert e2.get("1").found
    assert e2.get("3").found  # replayed from translog
    assert e2.get("3").source["n"] == 3
    e2.refresh()
    assert e2.stats()["docs"]["count"] == 3
    assert e2.tracker.max_seq_no == 2
    e2.close()


def test_recover_with_deletes(tmp_path):
    path = str(tmp_path / "shardY")
    e = Engine(path, MapperService(mappings=MAPPINGS))
    e.index("1", {"n": 1})
    e.flush()
    e.delete("1")
    e.index("2", {"n": 2})
    e.translog.sync()
    e.close()

    e2 = Engine(path, MapperService(mappings=MAPPINGS))
    assert not e2.get("1").found
    assert e2.get("2").found
    e2.close()


def test_merge_policy_bounds_segment_count(tmp_path):
    e = Engine(str(tmp_path / "shardM"), MapperService(mappings=MAPPINGS),
               merge_factor=3)
    for i in range(6):
        e.index(str(i), {"n": i})
        e.refresh()
    assert len(e.segments) <= 3
    assert e.stats()["docs"]["count"] == 6
    # all docs still findable after merges
    for i in range(6):
        assert e.get(str(i)).found
    e.close()


def test_force_merge(engine):
    for i in range(5):
        engine.index(str(i), {"n": i})
        engine.refresh()
    engine.force_merge(max_num_segments=1)
    assert len(engine.segments) == 1
    assert engine.stats()["docs"]["count"] == 5


def test_update_keeps_old_version_searchable_until_refresh(engine):
    """ES NRT semantics: updates/deletes invisible to search pre-refresh."""
    engine.index("1", {"body": "original text"})
    engine.refresh()
    engine.index("1", {"body": "updated text"})
    # search snapshot still sees exactly one live copy (the OLD one)
    snap = engine.acquire_searcher()
    assert snap.doc_count == 1
    assert all(s.live_doc_count == s.n_docs for s in snap.segments)
    # realtime GET sees the new version
    assert engine.get("1").source == {"body": "updated text"}
    engine.refresh()
    assert engine.stats()["docs"]["count"] == 1


def test_delete_invisible_until_refresh(engine):
    engine.index("1", {"n": 1})
    engine.refresh()
    engine.delete("1")
    assert engine.acquire_searcher().doc_count == 1  # still searchable
    assert not engine.get("1").found                 # realtime get: gone
    engine.refresh()
    assert engine.acquire_searcher().doc_count == 0
