"""Kernel correctness vs scalar references (model: AbstractQueryTestCase's
round-trip discipline — every kernel is property-tested against a pure
numpy implementation, SURVEY.md §4)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.ops import vector as vec_ops
from elasticsearch_tpu.ops.device import DeviceSegment, block_bucket

VOCAB = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
         "theta", "iota", "kappa"]


def random_corpus(rng, n_docs=500):
    # zipf-ish: earlier vocab words much more frequent
    probs = 1.0 / np.arange(1, len(VOCAB) + 1)
    probs /= probs.sum()
    docs = []
    for _ in range(n_docs):
        length = int(rng.integers(1, 40))
        words = rng.choice(VOCAB, size=length, p=probs)
        docs.append({"body": " ".join(words)})
    return docs


def build_device_segment(docs):
    svc = MapperService(mappings={"properties": {"body": {"type": "text"}}})
    w = SegmentWriter()
    for i, src in enumerate(docs):
        w.add(svc.parse(str(i), src))
    seg = w.build("s0")
    return seg, DeviceSegment(seg)


def test_bm25_kernel_matches_reference(rng):
    docs = random_corpus(rng)
    seg, dev = build_device_segment(docs)
    pf = seg.postings["body"]
    dp = dev.postings["body"]
    k1, b = 1.2, 0.75
    n = seg.n_docs

    query_terms = ["alpha", "gamma", "kappa", "notthere"]
    tids = [pf.term_id(t) for t in query_terms]
    idfs = [bm25_ops.idf(int(pf.doc_freq[tid]), pf.doc_count) if tid >= 0 else 0.0
            for tid in tids]

    sel, ws = dp.select_blocks(tids, idfs)
    scores = np.asarray(bm25_ops.bm25_block_scores(
        dp.block_docids, dp.block_tfs, sel, ws, dp.doc_lens,
        np.float32(dp.avg_len), k1, b))[:n]

    ref = bm25_ops.bm25_reference_scores(
        [pf.postings(t) for t in query_terms if pf.term_id(t) >= 0],
        [w for w, tid in zip(idfs, tids) if tid >= 0],
        pf.field_lengths, pf.avg_field_length, k1, b)
    np.testing.assert_allclose(scores, ref, rtol=2e-5, atol=1e-6)
    # non-matching docs are exactly zero
    matched = set()
    for t in query_terms:
        d, _ = pf.postings(t)
        matched.update(d.tolist())
    unmatched = [d for d in range(n) if d not in matched]
    assert np.all(scores[unmatched] == 0.0)


def test_bm25_topk_ordering_matches_reference(rng):
    docs = random_corpus(rng, 800)
    seg, dev = build_device_segment(docs)
    pf = seg.postings["body"]
    dp = dev.postings["body"]
    tids = [pf.term_id("alpha"), pf.term_id("beta")]
    idfs = [bm25_ops.idf(int(pf.doc_freq[t]), pf.doc_count) for t in tids]
    sel, ws = dp.select_blocks(tids, idfs)
    scores = bm25_ops.bm25_block_scores(
        dp.block_docids, dp.block_tfs, sel, ws, dp.doc_lens,
        np.float32(dp.avg_len), 1.2, 0.75)
    vals, ids = topk_ops.masked_topk(scores, dev.live & (scores > 0), 10)
    vals, ids = np.asarray(vals), np.asarray(ids)

    ref = bm25_ops.bm25_reference_scores(
        [pf.postings("alpha"), pf.postings("beta")], idfs,
        pf.field_lengths, pf.avg_field_length, 1.2, 0.75)
    order = np.lexsort((np.arange(len(ref)), -ref))[:10]
    np.testing.assert_array_equal(ids, order)
    np.testing.assert_allclose(vals, ref[order], rtol=2e-5)


def test_masked_topk_excludes_deleted_and_nonmatching(rng):
    docs = [{"body": "x common"}, {"body": "common"}, {"body": "other"}]
    seg, dev = build_device_segment(docs)
    seg.delete(0)
    dev = DeviceSegment(seg)
    pf, dp = seg.postings["body"], dev.postings["body"]
    tid = pf.term_id("common")
    sel, ws = dp.select_blocks([tid], [1.0])
    scores = bm25_ops.bm25_block_scores(
        dp.block_docids, dp.block_tfs, sel, ws, dp.doc_lens,
        np.float32(dp.avg_len), 1.2, 0.75)
    vals, ids = topk_ops.masked_topk(scores, dev.live & (scores > 0), 3)
    vals = np.asarray(vals)
    assert ids[0] == 1            # doc 0 deleted, doc 2 non-matching
    assert np.isinf(vals[1]) and vals[1] < 0
    assert np.isinf(vals[2]) and vals[2] < 0


def test_match_mask_and_count(rng):
    docs = [{"body": "a b"}, {"body": "a"}, {"body": "b c"}, {"body": "c"}]
    seg, dev = build_device_segment(docs)
    pf, dp = seg.postings["body"], dev.postings["body"]
    sel_a, _ = dp.select_blocks([pf.term_id("a")], [1.0])
    mask = np.asarray(bm25_ops.match_mask(
        dp.block_docids, dp.block_tfs, sel_a, dev.n_docs_padded))
    assert mask[:4].tolist() == [True, True, False, False]

    # two clauses: (a) and (b) — docs matching both: only doc 0
    sel_b, _ = dp.select_blocks([pf.term_id("b")], [1.0])
    sel = np.concatenate([sel_a, sel_b])
    cids = np.concatenate([np.zeros(len(sel_a), np.int32),
                           np.ones(len(sel_b), np.int32)])
    counts = np.asarray(bm25_ops.match_count(
        dp.block_docids, dp.block_tfs, sel, cids, 2, dev.n_docs_padded))
    assert counts[:4].tolist() == [2, 1, 1, 0]


def test_block_max_is_upper_bound(rng):
    docs = random_corpus(rng, 400)
    seg, dev = build_device_segment(docs)
    pf, dp = seg.postings["body"], dev.postings["body"]
    k1, b = 1.2, 0.75
    for term in ["alpha", "iota"]:
        tid = pf.term_id(term)
        w = bm25_ops.idf(int(pf.doc_freq[tid]), pf.doc_count)
        sel, ws = dp.select_blocks([tid], [w])
        bounds = np.asarray(bm25_ops.block_max_scores(
            dp.block_max_tf, dp.block_min_len, sel, ws,
            np.float32(dp.avg_len), k1, b))
        scores = np.asarray(bm25_ops.bm25_block_scores(
            dp.block_docids, dp.block_tfs, sel, ws, dp.doc_lens,
            np.float32(dp.avg_len), k1, b))
        assert scores.max() <= bounds.max() + 1e-5


def test_merge_topk_tie_break():
    va = np.array([3.0, 1.0], np.float32)
    ia = np.array([5, 7], np.int32)
    vb = np.array([3.0, 2.0], np.float32)
    ib = np.array([2, 9], np.int32)
    v, i = topk_ops.merge_topk(va, ia, vb, ib, 3)
    assert np.asarray(v).tolist() == [3.0, 3.0, 2.0]
    assert np.asarray(i).tolist() == [2, 5, 9]  # tie at 3.0 → lower id first


def test_cosine_dot_l2_match_reference(rng):
    nd, d = 200, 32
    vectors = rng.standard_normal((nd, d)).astype(np.float32)
    vectors[17] = 0.0  # zero vector edge case
    queries = rng.standard_normal((3, d)).astype(np.float32)

    # float32 path: exact parity
    import jax.numpy as jnp
    prepped, norms = vec_ops.prepare_vectors(vectors, "cosine", np.float32)
    cos = np.asarray(vec_ops.cosine_scores(queries, prepped))
    for qi in range(3):
        np.testing.assert_allclose(
            cos[qi], vec_ops.cosine_reference(queries[qi], vectors),
            rtol=1e-5, atol=1e-5)

    prepped, norms = vec_ops.prepare_vectors(vectors, "dot", np.float32)
    dots = np.asarray(vec_ops.dot_scores(queries, prepped))
    for qi in range(3):
        np.testing.assert_allclose(
            dots[qi], vec_ops.dot_reference(queries[qi], vectors), rtol=1e-4)

    l2 = np.asarray(vec_ops.l2_scores(queries, prepped, norms * norms))
    for qi in range(3):
        np.testing.assert_allclose(
            l2[qi], vec_ops.l2_reference(queries[qi], vectors),
            rtol=1e-3, atol=1e-3)


def test_bf16_cosine_recall(rng):
    """bf16 slab must preserve top-k recall ≥ 0.9 vs float32 exact."""
    nd, d = 2000, 64
    vectors = rng.standard_normal((nd, d)).astype(np.float32)
    query = rng.standard_normal((1, d)).astype(np.float32)
    prepped16, _ = vec_ops.prepare_vectors(vectors, "cosine")
    approx = np.asarray(vec_ops.cosine_scores(query, prepped16))[0]
    exact = vec_ops.cosine_reference(query[0], vectors)
    k = 100
    top_approx = set(np.argsort(-approx)[:k].tolist())
    top_exact = set(np.argsort(-exact)[:k].tolist())
    assert len(top_approx & top_exact) / k >= 0.9


def test_block_bucket():
    assert block_bucket(1) == 8
    assert block_bucket(8) == 8
    assert block_bucket(9) == 16
    assert block_bucket(1000) == 1024


def test_device_segment_padding(rng):
    docs = random_corpus(rng, 10)
    seg, dev = build_device_segment(docs)
    assert dev.n_docs_padded % 1024 == 0
    live = np.asarray(dev.live)
    assert live[: seg.n_docs].all()
    assert not live[seg.n_docs:].any()


def test_bm25_sorted_topk_batch_matches_single():
    """The batched (vmapped) kernel must agree with per-query launches
    (the continuous-batching serving path)."""
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.bm25 import (bm25_sorted_topk,
                                            bm25_sorted_topk_batch)
    rng = np.random.default_rng(9)
    tb, blk, nd = 17, 8, 60
    docids = rng.integers(0, nd, size=(tb, blk)).astype(np.int32)
    tfs = rng.integers(0, 4, size=(tb, blk)).astype(np.float32)
    docids[-1] = 0
    tfs[-1] = 0.0                       # reserved zero block
    lens = rng.uniform(5, 50, nd).astype(np.float32)
    live = np.ones(nd, bool)
    sels = np.array([[0, 3, 5, 16], [1, 2, 16, 16], [7, 8, 9, 10]],
                    np.int32)
    ws = np.array([[1.0, 0.5, 0.25, 0.0], [2.0, 1.0, 0.0, 0.0],
                   [1.0, 1.0, 1.0, 1.0]], np.float32)
    k = 10
    bvals, bids = bm25_sorted_topk_batch(
        jnp.asarray(docids), jnp.asarray(tfs), jnp.asarray(sels),
        jnp.asarray(ws), jnp.asarray(lens), jnp.asarray(live),
        np.float32(lens.mean()), 1.2, 0.75, k)
    for qi in range(len(sels)):
        svals, sids = bm25_sorted_topk(
            jnp.asarray(docids), jnp.asarray(tfs),
            jnp.asarray(sels[qi]), jnp.asarray(ws[qi]),
            jnp.asarray(lens), jnp.asarray(live),
            np.float32(lens.mean()), 1.2, 0.75, k)
        np.testing.assert_allclose(np.asarray(bvals[qi]),
                                   np.asarray(svals), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(bids[qi]),
                                      np.asarray(sids))


def test_pallas_bm25_contrib_matches_reference():
    """The Pallas contribution kernel is bit-compatible (to float32
    rounding) with the jnp expression used by the hot path; on CPU it
    runs in interpret mode."""
    import jax.numpy as jnp
    from elasticsearch_tpu.ops.pallas_bm25 import (bm25_contrib_pallas,
                                                   contrib_reference)
    rng = np.random.default_rng(3)
    for nb in (64, 256, 512):
        tf = rng.integers(0, 5, size=(nb, 128)).astype(np.float32)
        dl = rng.uniform(5, 200, size=(nb, 128)).astype(np.float32)
        # padding lanes: tf=0 must contribute exactly 0
        tf[:, -7:] = 0.0
        w = rng.uniform(0.5, 8.0, nb).astype(np.float32)
        out = np.asarray(bm25_contrib_pallas(w, tf, dl, 40.0, 1.2, 0.75))
        ref = np.asarray(contrib_reference(
            jnp.asarray(w), jnp.asarray(tf), jnp.asarray(dl),
            40.0, 1.2, 0.75))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        assert (out[:, -7:] == 0.0).all()
