"""Driver entry-path platform pinning (__graft_entry__.py).

Regression for the r04/r05 wedge class: a driver that exports
``JAX_PLATFORMS=cpu`` must get the cpu backend on EVERY entry path —
importing the package, building the entry step, and the multichip
dryrun — never a device backend that can hang the process on a dead
relay. The checks run in a subprocess because backend selection is a
process-global, one-shot decision.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str, timeout: int = 240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_PLATFORM_NAME", None)
    return subprocess.run([sys.executable, "-c", code], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=timeout)


def test_import_and_entry_stay_on_cpu():
    code = (
        "import elasticsearch_tpu\n"
        "import sys, os\n"
        "sys.path.insert(0, os.getcwd())\n"
        "import __graft_entry__ as g\n"
        "import jax\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "assert jax.default_backend() == 'cpu', jax.default_backend()\n"
        "assert all(d.platform == 'cpu' for d in jax.devices()), "
        "jax.devices()\n"
        "print('CPU-PIN-OK')\n")
    r = _run(code)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "CPU-PIN-OK" in r.stdout


def test_multichip_dryrun_emits_sectioned_json_on_cpu():
    """dryrun_multichip under the cpu pin: the preflight section is
    skipped (cpu pinned by caller), every section records a status into
    the incrementally-printed JSON line — the parseable-record contract
    for rc=124 rounds. Sections may fail on environments whose jax
    lacks shard_map; the JSON record (not success) is the contract."""
    code = (
        "import os, sys, json\n"
        "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS', '') + "
        "' --xla_force_host_platform_device_count=2'\n"
        "sys.path.insert(0, os.getcwd())\n"
        "import __graft_entry__ as g\n"
        "try:\n"
        "    g.dryrun_multichip(2)\n"
        "except Exception:\n"
        "    pass\n")
    r = _run(code, timeout=420)
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert lines, (r.stdout, r.stderr)
    payload = json.loads(lines[-1])
    assert payload["n_devices"] == 2
    sections = payload["sections"]
    assert sections["preflight"]["ok"] is True
    assert "skipped" in sections["preflight"]
    assert "backend_init" in sections
    for sec in sections.values():
        assert "ok" in sec
