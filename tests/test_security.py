"""Security tests: authn (basic + API keys), RBAC authz, DLS/FLS (model:
the reference's AuthenticationServiceTests, AuthorizationServiceTests,
DocumentSubsetReaderTests, FieldSubsetReaderTests)."""

import base64
import tempfile

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.xpack.security import (
    AuthenticationException,
    SecurityException,
    SecurityService,
    User,
    required_privilege,
)


def basic(user, password):
    return {"Authorization": "Basic " + base64.b64encode(
        f"{user}:{password}".encode()).decode()}


@pytest.fixture()
def node():
    n = Node(settings=Settings.from_dict({
        "xpack.security.enabled": True,
        "bootstrap.password": "s3cret"}),
        data_path=tempfile.mkdtemp())
    yield n
    n.close()


ELASTIC = None  # filled per test via basic("elastic", "s3cret")


# ---- unit: service ----

def test_password_auth_roundtrip():
    svc = SecurityService(enabled=True, bootstrap_password="pw")
    user = svc.authenticate({"Authorization": "Basic " + base64.b64encode(
        b"elastic:pw").decode()})
    assert user.username == "elastic"
    assert svc.has_cluster_privilege(user, "all")
    with pytest.raises(AuthenticationException):
        svc.authenticate({"Authorization": "Basic " + base64.b64encode(
            b"elastic:wrong").decode()})
    with pytest.raises(AuthenticationException):
        svc.authenticate({})


def test_rbac_privilege_implication():
    svc = SecurityService(enabled=True)
    svc.put_role("writer", {"cluster": ["monitor"], "indices": [
        {"names": ["logs-*"], "privileges": ["write", "read"]}]})
    svc.put_user("bob", {"password": "pw12345", "roles": ["writer"]})
    u = svc.authenticate(
        {"Authorization": "Basic " + base64.b64encode(b"bob:pw12345").decode()})
    assert svc.has_index_privilege(u, "logs-2024", "index")   # write implies
    assert svc.has_index_privilege(u, "logs-2024", "read")
    assert not svc.has_index_privilege(u, "secrets", "read")  # pattern miss
    assert not svc.has_cluster_privilege(u, "manage_security")
    with pytest.raises(SecurityException):
        svc.authorize(u, "index", "read", "secrets")


def test_api_key_lifecycle():
    svc = SecurityService(enabled=True)
    svc.put_user("app", {"password": "pw12345", "roles": ["superuser"]})
    owner = User("app", ["superuser"])
    created = svc.create_api_key(owner, {"name": "ci"})
    hdr = {"Authorization": "ApiKey " + created["encoded"]}
    u = svc.authenticate(hdr)
    assert u.username == "app"
    assert svc.has_cluster_privilege(u, "all")
    svc.invalidate_api_key(key_id=created["id"])
    with pytest.raises(AuthenticationException):
        svc.authenticate(hdr)


def test_api_key_role_descriptors_limit_privileges():
    svc = SecurityService(enabled=True)
    owner = User("app", ["superuser"])
    created = svc.create_api_key(owner, {"name": "limited",
        "role_descriptors": {"ro": {"indices": [
            {"names": ["public-*"], "privileges": ["read"]}]}}})
    u = svc.authenticate({"Authorization": "ApiKey " + created["encoded"]})
    assert u.username == "app"
    assert svc.has_index_privilege(u, "public-1", "read")
    assert not svc.has_index_privilege(u, "private", "read")
    assert not svc.has_cluster_privilege(u, "all")


def test_required_privilege_mapping():
    assert required_privilege("POST", "/logs/_search") == ("index", "read", "logs")
    assert required_privilege("PUT", "/logs/_doc/1") == ("index", "write", "logs")
    assert required_privilege("PUT", "/logs") == ("index", "create_index", "logs")
    assert required_privilege("DELETE", "/logs") == ("index", "delete_index", "logs")
    assert required_privilege("GET", "/_cluster/health")[0] == "cluster"
    assert required_privilege("PUT", "/_security/role/x") == (
        "cluster", "manage_security", None)
    assert required_privilege("POST", "/_bulk") == ("index", "write", "*")


# ---- REST integration ----

def test_rest_requires_auth(node):
    c = node.rest_controller
    s, r = c.dispatch("GET", "/_cluster/health", None, None)
    assert s == 401
    s, r = c.dispatch("GET", "/_cluster/health", None, None,
                      headers=basic("elastic", "s3cret"))
    assert s == 200, r


def test_rest_user_crud_and_rbac(node):
    c = node.rest_controller
    el = basic("elastic", "s3cret")
    s, r = c.dispatch("PUT", "/_security/role/reader", None, {
        "cluster": ["monitor"],
        "indices": [{"names": ["public*"], "privileges": ["read"]}]},
        headers=el)
    assert s == 200, r
    s, r = c.dispatch("PUT", "/_security/user/alice", None,
                      {"password": "alicepw1", "roles": ["reader"]},
                      headers=el)
    assert s == 200 and r["created"]
    al = basic("alice", "alicepw1")
    # authorized: read on public*
    c.dispatch("PUT", "/public1", None, None, headers=el)
    node.indices_service.get("public1").index_doc("1", {"v": 1})
    node.indices_service.get("public1").refresh()
    s, r = c.dispatch("POST", "/public1/_search", None, None, headers=al)
    assert s == 200 and r["hits"]["total"]["value"] == 1
    # denied: write
    s, r = c.dispatch("PUT", "/public1/_doc/2", None, {"v": 2}, headers=al)
    assert s == 403
    # denied: other index
    c.dispatch("PUT", "/private1", None, None, headers=el)
    s, r = c.dispatch("POST", "/private1/_search", None, None, headers=al)
    assert s == 403
    # denied: manage security
    s, r = c.dispatch("PUT", "/_security/role/evil", None, {}, headers=al)
    assert s == 403
    # _authenticate works for any authenticated user
    s, r = c.dispatch("GET", "/_security/_authenticate", None, None, headers=al)
    assert s == 200 and r["username"] == "alice"


def test_dls_filters_documents(node):
    c = node.rest_controller
    el = basic("elastic", "s3cret")
    c.dispatch("PUT", "/events", None, {"mappings": {"properties": {
        "team": {"type": "keyword"}, "msg": {"type": "text"}}}}, headers=el)
    idx = node.indices_service.get("events")
    idx.index_doc("1", {"team": "red", "msg": "alpha"})
    idx.index_doc("2", {"team": "blue", "msg": "beta"})
    idx.index_doc("3", {"team": "red", "msg": "gamma"})
    idx.refresh()
    c.dispatch("PUT", "/_security/role/red_only", None, {
        "indices": [{"names": ["events"], "privileges": ["read"],
                     "query": {"term": {"team": "red"}}}]}, headers=el)
    c.dispatch("PUT", "/_security/user/red", None,
               {"password": "redpass1", "roles": ["red_only"]}, headers=el)
    s, r = c.dispatch("POST", "/events/_search", None, None,
                      headers=basic("red", "redpass1"))
    assert s == 200, r
    ids = {h["_id"] for h in r["hits"]["hits"]}
    assert ids == {"1", "3"}
    # superuser sees everything
    s, r = c.dispatch("POST", "/events/_search", None, None, headers=el)
    assert r["hits"]["total"]["value"] == 3
    # DLS also applies to _count
    s, r = c.dispatch("POST", "/events/_count", None, None,
                      headers=basic("red", "redpass1"))
    assert r["count"] == 2


def test_fls_filters_fields(node):
    c = node.rest_controller
    el = basic("elastic", "s3cret")
    c.dispatch("PUT", "/people", None, None, headers=el)
    idx = node.indices_service.get("people")
    idx.index_doc("1", {"name": "ann", "ssn": "123-45-6789", "age": 44})
    idx.refresh()
    c.dispatch("PUT", "/_security/role/no_pii", None, {
        "indices": [{"names": ["people"], "privileges": ["read"],
                     "field_security": {"grant": ["*"],
                                        "except": ["ssn"]}}]}, headers=el)
    c.dispatch("PUT", "/_security/user/hr", None,
               {"password": "hrpass12", "roles": ["no_pii"]}, headers=el)
    s, r = c.dispatch("POST", "/people/_search", None, None,
                      headers=basic("hr", "hrpass12"))
    assert s == 200
    src = r["hits"]["hits"][0]["_source"]
    assert "ssn" not in src and src["name"] == "ann" and src["age"] == 44
    # superuser still sees the field
    s, r = c.dispatch("POST", "/people/_search", None, None, headers=el)
    assert "ssn" in r["hits"]["hits"][0]["_source"]


def test_security_disabled_no_auth_needed():
    n = Node(data_path=tempfile.mkdtemp())
    try:
        s, r = n.rest_controller.dispatch("GET", "/_cluster/health", None, None)
        assert s == 200
    finally:
        n.close()


def test_change_password(node):
    c = node.rest_controller
    el = basic("elastic", "s3cret")
    c.dispatch("PUT", "/_security/user/carol", None,
               {"password": "first123", "roles": ["superuser"]}, headers=el)
    s, r = c.dispatch("PUT", "/_security/user/carol/_password", None,
                      {"password": "second45"}, headers=el)
    assert s == 200
    s, _ = c.dispatch("GET", "/_cluster/health", None, None,
                      headers=basic("carol", "first123"))
    assert s == 401
    s, _ = c.dispatch("GET", "/_cluster/health", None, None,
                      headers=basic("carol", "second45"))
    assert s == 200


def test_anonymous_access(tmp_path):
    """xpack.security.authc.anonymous.* grants credential-less requests a
    principal with the configured roles (ref: AnonymousUser)."""
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"anonymous": {"username": "anon",
                                    "roles": "viewer"}}}},
        "bootstrap": {"password": "secret123"}}),
        data_path=str(tmp_path / "d"))
    try:
        n.security_service.put_role("viewer", {
            "cluster": ["monitor"],
            "indices": [{"names": ["*"], "privileges": ["read"]}]})
        # anonymous request: no Authorization header at all
        status, r = n.rest_controller.dispatch(
            "GET", "/_security/_authenticate", {}, None, headers={})
        assert status == 200
        assert r["username"] == "anon"
        assert r["roles"] == ["viewer"]
        # reads allowed, writes denied by the viewer role
        n.indices_service.create_index("open", {}, None)
        idx = n.indices_service.get("open")
        idx.index_doc("1", {"v": 1})
        idx.refresh()
        status, _ = n.rest_controller.dispatch(
            "POST", "/open/_search", {}, {"size": 1}, headers={})
        assert status == 200
        status, _ = n.rest_controller.dispatch(
            "PUT", "/open/_doc/2", {}, {"v": 2}, headers={})
        assert status == 403
    finally:
        n.close()


def test_anonymous_roles_alone_and_unknown_scheme(tmp_path):
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"anonymous": {"roles": "viewer,"}}}},
        "bootstrap": {"password": "secret123"}}),
        data_path=str(tmp_path / "d"))
    try:
        n.security_service.put_role("viewer", {"cluster": ["monitor"]})
        status, r = n.rest_controller.dispatch(
            "GET", "/_security/_authenticate", {}, None, headers={})
        assert status == 200
        # username defaults like the reference; trailing comma filtered
        assert r["username"] == "_anonymous"
        assert r["roles"] == ["viewer"]
        # unconsumable auth scheme falls back to anonymous, not 401
        status, r = n.rest_controller.dispatch(
            "GET", "/_security/_authenticate", {}, None,
            headers={"Authorization": "Negotiate abc"})
        assert status == 200
        assert r["username"] == "_anonymous"
    finally:
        n.close()
