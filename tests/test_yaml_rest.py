"""Declarative YAML REST suites (SURVEY.md §4 tier 5 — the
ESClientYamlSuiteTestCase model): suites in tests/yaml_suites/ run
against a fresh in-process node per test."""

import glob
import os

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.testing.yaml_rest import YamlRestRunner

SUITES = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "yaml_suites", "*.yml")))


@pytest.mark.parametrize("suite", SUITES,
                         ids=[os.path.basename(s) for s in SUITES])
def test_yaml_suite(suite, tmp_path):
    counter = [0]

    def factory():
        counter[0] += 1
        return Node(data_path=str(tmp_path / f"n{counter[0]}"))

    YamlRestRunner(factory).run_file(suite)
