"""Declarative YAML REST suites (SURVEY.md §4 tier 5 — the
ESClientYamlSuiteTestCase model): suites in tests/yaml_suites/ run
against a fresh in-process node per test. Suites named ``9[0-3]_dist*``
run against a 3-NODE sim cluster instead (``ClusterYamlAdapter``
bridges the runner's ``rest_controller.dispatch`` seam onto
ClusterNode client calls) so multi-node response shapes — distributed
aggregations included — pin through the same declarative format."""

import glob
import os

import pytest

from elasticsearch_tpu.common.errors import ElasticsearchTpuException
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.testing.yaml_rest import YamlRestRunner

ALL_SUITES = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "yaml_suites", "*.yml")))


def _seconds(tv):
    """YAML keep-alives ("30s"/"5m") → scheduler-clock seconds."""
    from elasticsearch_tpu.common.settings import parse_time_value
    return parse_time_value(str(tv), "keep_alive")
CLUSTER_SUITES = [s for s in ALL_SUITES
                  if os.path.basename(s).startswith("93_")]
SUITES = [s for s in ALL_SUITES if s not in CLUSTER_SUITES]


@pytest.mark.parametrize("suite", SUITES,
                         ids=[os.path.basename(s) for s in SUITES])
def test_yaml_suite(suite, tmp_path):
    counter = [0]

    def factory():
        counter[0] += 1
        return Node(data_path=str(tmp_path / f"n{counter[0]}"))

    YamlRestRunner(factory).run_file(suite)


class ClusterYamlAdapter:
    """A 3-node SimDataCluster behind the yaml runner's node seam: the
    adapter IS its own ``rest_controller`` and maps the handful of
    APIs the distributed suites use onto the cluster client calls,
    driving the deterministic queue around each one."""

    def __init__(self, tmp_path, seed=29):
        from test_cluster_node import SimDataCluster
        self.cluster = SimDataCluster(3, tmp_path, seed=seed)
        self.master = self.cluster.stabilise()
        self.rest_controller = self

    def close(self):
        for cn in self.cluster.cluster_nodes.values():
            try:
                cn.stop()
            except Exception:   # noqa: BLE001 — teardown best effort
                pass

    # ------------------------------------------------------- dispatch
    def dispatch(self, method, path, params, body):
        import re
        params = params or {}
        try:
            m = re.fullmatch(r"/([^/]+)", path)
            if m and method == "PUT":
                return 200, self._create_index(m.group(1), body or {})
            m = re.fullmatch(r"/([^/]+)/_doc/([^/]+)", path)
            if m and method == "PUT":
                resp = self.cluster.call(
                    self.master.bulk, m.group(1),
                    [{"op": "index", "id": m.group(2), "source": body}])
                item = resp["items"][0]
                if "error" in item:
                    return 400, {"error": item["error"], "status": 400}
                return 201, {"result": "created", "_id": m.group(2)}
            m = re.fullmatch(r"/([^/]+)/_refresh", path)
            if m:
                self.cluster.call(self.master.refresh)
                self.cluster.run_for(5)
                return 200, {"_shards": {}}
            m = re.fullmatch(r"/([^/]+)/_search", path)
            if m:
                body = dict(body or {})
                if "allow_partial_search_results" in params:
                    body["allow_partial_search_results"] = \
                        params["allow_partial_search_results"]
                if "scroll" in params:
                    resp = self.cluster.call(
                        self.master.search, m.group(1), body,
                        scroll=_seconds(params["scroll"]))
                else:
                    resp = self.cluster.call(self.master.search,
                                             m.group(1), body)
                return 200, resp
            if path == "/_search" and method in ("GET", "POST"):
                # PIT searches target no index — the pit id IS the scope
                return 200, self.cluster.call(self.master.search,
                                              "_all", dict(body or {}))
            if path == "/_search/scroll" and method in ("POST", "GET"):
                b = dict(body or {})
                sid = b.get("scroll_id") or params.get("scroll_id")
                keep = b.get("scroll") or params.get("scroll")
                return 200, self.cluster.call(
                    self.master.scroll, sid,
                    _seconds(keep) if keep else None)
            if path == "/_search/scroll" and method == "DELETE":
                ids = (body or {}).get("scroll_id", ["_all"])
                if isinstance(ids, str):
                    ids = [ids]
                return 200, self.cluster.call(self.master.clear_scroll,
                                              ids)
            m = re.fullmatch(r"/([^/]+)/_pit", path)
            if m and method == "POST":
                return 200, self.cluster.call(
                    self.master.open_pit, m.group(1),
                    _seconds(params.get("keep_alive", "5m")))
            if path == "/_pit" and method == "DELETE":
                return 200, self.cluster.call(self.master.close_pit,
                                              (body or {})["id"])
            m = re.fullmatch(r"/([^/]+)/_async_search", path)
            if m and method == "POST":
                return 200, self.cluster.call(
                    self.master.submit_async_search, m.group(1),
                    dict(body or {}), dict(params))
            m = re.fullmatch(r"/_async_search/([^/]+)", path)
            if m and method == "GET":
                return 200, self.cluster.call(
                    self.master.get_async_search, m.group(1),
                    dict(params))
            if m and method == "DELETE":
                return 200, self.cluster.call(
                    self.master.delete_async_search, m.group(1))
            # ------------------------------------------ snapshot plane
            m = re.fullmatch(r"/_snapshot/([^/]+)", path)
            if m and method in ("PUT", "POST"):
                return 200, self.cluster.call(
                    self.master.put_repository, m.group(1), body or {})
            if m and method == "GET":
                return 200, self.master.get_repositories(m.group(1))
            m = re.fullmatch(r"/_snapshot/([^/]+)/([^/]+)/_status", path)
            if m and method == "GET":
                return 200, self.cluster.call(
                    self.master.snapshot_status, m.group(1), m.group(2))
            m = re.fullmatch(r"/_snapshot/([^/]+)/([^/]+)/_restore", path)
            if m and method == "POST":
                resp = self.cluster.call(
                    self.master.restore_snapshot, m.group(1), m.group(2),
                    body or {})
                self.cluster.run_for(60)
                return 200, resp
            m = re.fullmatch(r"/_snapshot/([^/]+)/([^/]+)", path)
            if m and method in ("PUT", "POST"):
                wait = params.get("wait_for_completion", "true") != "false"
                return 200, self.cluster.call(
                    self.master.create_snapshot, m.group(1), m.group(2),
                    body or {}, wait_for_completion=wait)
            if m and method == "GET":
                snap = None if m.group(2) in ("_all", "*") else m.group(2)
                return 200, self.cluster.call(
                    self.master.get_snapshots, m.group(1), snap)
            if m and method == "DELETE":
                return 200, self.cluster.call(
                    self.master.delete_snapshot, m.group(1), m.group(2))
            m = re.fullmatch(r"/_slm/policy/([^/]+)/_execute", path)
            if m and method == "POST":
                return 200, self.cluster.call(
                    self.master.slm_request, "execute", m.group(1))
            m = re.fullmatch(r"/_slm/policy/([^/]+)", path)
            if m and method == "PUT":
                return 200, self.cluster.call(
                    self.master.slm_request, "put", m.group(1),
                    body or {})
            if m and method == "GET":
                return 200, self.cluster.call(
                    self.master.slm_request, "get", m.group(1))
            if m and method == "DELETE":
                return 200, self.cluster.call(
                    self.master.slm_request, "delete", m.group(1))
            if path == "/_slm/policy" and method == "GET":
                return 200, self.cluster.call(
                    self.master.slm_request, "get")
            m = re.fullmatch(r"/_tasks/([^/]+)", path)
            if m and method == "GET":
                return 200, self.cluster.call(
                    self.master.get_task, m.group(1))
        except ElasticsearchTpuException as e:
            return e.status, {
                "error": {**e.to_xcontent(),
                          "root_cause": [e.to_xcontent()]},
                "status": e.status}
        except Exception as e:  # noqa: BLE001 — typed 500, like the
            # RestController's Throwable barrier
            from elasticsearch_tpu.common.errors import snake_case
            return 500, {"error": {"type": snake_case(type(e).__name__),
                                   "reason": str(e)}, "status": 500}
        return 405, {"error": {
            "type": "unsupported_api",
            "reason": f"cluster yaml adapter: {method} {path}"},
            "status": 405}

    def _create_index(self, index, body):
        settings = body.get("settings") or {}
        shards = int(settings.get("index.number_of_shards",
                                  settings.get("number_of_shards", 1)))
        replicas = int(settings.get("index.number_of_replicas",
                                    settings.get("number_of_replicas",
                                                 0)))
        resp = self.cluster.call(
            self.master.create_index, index, number_of_shards=shards,
            number_of_replicas=replicas,
            mappings=body.get("mappings"))
        self.cluster.run_for(60)
        return resp


@pytest.mark.parametrize("suite", CLUSTER_SUITES,
                         ids=[os.path.basename(s)
                              for s in CLUSTER_SUITES])
def test_cluster_yaml_suite(suite, tmp_path):
    counter = [0]

    def factory():
        counter[0] += 1
        return ClusterYamlAdapter(tmp_path / f"c{counter[0]}")

    YamlRestRunner(factory).run_file(suite)
