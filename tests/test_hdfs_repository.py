"""repository-hdfs against an in-process WebHDFS fixture (the
reference's hdfs-fixture strategy, ref: plugins/repository-hdfs +
test/fixtures/hdfs-fixture): the fixture emulates a namenode —
including the namenode→datanode 307-redirect protocol for data
operations — and verifies the client sends ``user.name``."""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.repositories.hdfs import (
    HdfsBlobContainer,
    _endpoint_from_uri,
)


class _WebHdfsHandler(BaseHTTPRequestHandler):
    """Minimal WebHDFS namenode: files live in ``server.files``;
    CREATE and OPEN answer 307 to ``?datanode=true`` first, like a real
    namenode handing out a datanode location."""

    def log_message(self, *a):
        pass

    def _send(self, status, body=b"", headers=()):
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _parse(self):
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query))
        assert u.path.startswith("/webhdfs/v1"), u.path
        return u.path[len("/webhdfs/v1"):], q

    def _redirected(self, q):
        return q.get("datanode") == "true"

    def _redirect(self, path, q):
        q = dict(q)
        q["datanode"] = "true"
        host, port = self.server.server_address[:2]
        loc = (f"http://{host}:{port}/webhdfs/v1"
               f"{urllib.parse.quote(path)}?"
               + urllib.parse.urlencode(q))
        self._send(307, b"", [("Location", loc)])

    def do_PUT(self):
        path, q = self._parse()
        self.server.users.add(q.get("user.name"))
        op = q.get("op", "").upper()
        if op == "MKDIRS":
            self._send(200, b'{"boolean": true}')
            return
        assert op == "CREATE", op
        if not self._redirected(q):
            self._redirect(path, q)
            return
        if (q.get("overwrite") == "false"
                and path in self.server.files):
            self._send(403, json.dumps({"RemoteException": {
                "exception": "FileAlreadyExistsException"}}).encode())
            return
        n = int(self.headers.get("Content-Length") or 0)
        self.server.files[path] = self.rfile.read(n) if n else b""
        self._send(201)

    def do_GET(self):
        path, q = self._parse()
        self.server.users.add(q.get("user.name"))
        op = q.get("op", "").upper()
        if op == "GETFILESTATUS":
            if path in self.server.files:
                self._send(200, json.dumps({"FileStatus": {
                    "type": "FILE",
                    "length": len(self.server.files[path])}}).encode())
            else:
                self._send(404, json.dumps({"RemoteException": {
                    "exception": "FileNotFoundException"}}).encode())
            return
        if op == "LISTSTATUS":
            prefix = path.rstrip("/") + "/"
            entries = [{"pathSuffix": p[len(prefix):], "type": "FILE",
                        "length": len(v)}
                       for p, v in self.server.files.items()
                       if p.startswith(prefix)
                       and "/" not in p[len(prefix):]]
            if not entries and not any(
                    p.startswith(prefix) for p in self.server.files):
                self._send(404, b"{}")
                return
            self._send(200, json.dumps(
                {"FileStatuses": {"FileStatus": entries}}).encode())
            return
        assert op == "OPEN", op
        if path not in self.server.files:
            self._send(404)
            return
        if not self._redirected(q):
            self._redirect(path, q)
            return
        self._send(200, self.server.files[path])

    def do_DELETE(self):
        path, q = self._parse()
        existed = self.server.files.pop(path, None) is not None
        self._send(200, json.dumps({"boolean": existed}).encode())


@pytest.fixture()
def webhdfs():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _WebHdfsHandler)
    srv.files = {}
    srv.users = set()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _endpoint(srv):
    host, port = srv.server_address[:2]
    return f"{host}:{port}"


def test_uri_schemes():
    assert _endpoint_from_uri("hdfs://nn:9870") == "http://nn:9870"
    assert _endpoint_from_uri("webhdfs://nn:9870") == "http://nn:9870"
    assert _endpoint_from_uri("https://nn:9871") == "https://nn:9871"
    from elasticsearch_tpu.common.errors import IllegalArgumentException
    with pytest.raises(IllegalArgumentException):
        _endpoint_from_uri("ftp://nn:21")
    with pytest.raises(IllegalArgumentException):
        _endpoint_from_uri("hdfs://")


def test_blob_container_contract(webhdfs):
    c = HdfsBlobContainer(f"http://{_endpoint(webhdfs)}", "base/seg0",
                          user="elastic")
    c.write_blob("blob-a", b"alpha")
    c.write_blob("blob-b", b"beta" * 1000)
    assert c.read_blob("blob-a") == b"alpha"
    assert c.read_blob("blob-b") == b"beta" * 1000
    assert c.blob_exists("blob-a")
    assert not c.blob_exists("missing")
    assert c.list_blobs() == ["blob-a", "blob-b"]
    # fail_if_exists surfaces the 403 FileAlreadyExistsException
    from elasticsearch_tpu.repositories.blobstore import (
        RepositoryException)
    with pytest.raises(RepositoryException):
        c.write_blob("blob-a", b"clobber", fail_if_exists=True)
    c.delete_blob("blob-a")
    assert not c.blob_exists("blob-a")
    assert c.list_blobs() == ["blob-b"]
    from elasticsearch_tpu.common.errors import ResourceNotFoundException
    with pytest.raises(ResourceNotFoundException):
        c.read_blob("blob-a")
    # simple-auth principal rode every request
    assert "elastic" in webhdfs.users


def test_snapshot_restore_roundtrip(tmp_path, webhdfs):
    node = Node(data_path=str(tmp_path / "data"))
    try:
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/hdfs_repo", None,
            {"type": "hdfs", "settings": {
                "uri": f"hdfs://{_endpoint(webhdfs)}",
                "path": "/elasticsearch/repositories/repo1",
                "security.principal": "elasticsearch@REALM"}})
        assert st == 200, r
        node.rest_controller.dispatch("PUT", "/docs", None, {
            "mappings": {"properties": {"t": {"type": "text"}}}})
        for i in range(20):
            node.rest_controller.dispatch(
                "PUT", f"/docs/_doc/{i}", None,
                {"t": f"hadoop elephant {i}"})
        node.rest_controller.dispatch("POST", "/docs/_refresh", None, None)
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/hdfs_repo/snap1",
            {"wait_for_completion": "true"}, {"indices": "docs"})
        assert st == 200, r
        # the snapshot physically lives in the fixture's filesystem
        assert any("repositories/repo1" in p for p in webhdfs.files)
        # the kerberos realm was stripped from the principal
        assert "elasticsearch" in webhdfs.users
        st, r = node.rest_controller.dispatch(
            "POST", "/_snapshot/hdfs_repo/snap1/_restore", None,
            {"indices": "docs", "rename_pattern": "^docs$",
             "rename_replacement": "docs2"})
        assert st == 200, r
        st, r = node.rest_controller.dispatch(
            "POST", "/docs2/_search", None,
            {"query": {"match": {"t": "elephant"}}, "size": 30})
        assert st == 200 and r["hits"]["total"]["value"] == 20
    finally:
        node.close()


def test_missing_settings_rejected(tmp_path, webhdfs):
    node = Node(data_path=str(tmp_path / "data"))
    try:
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/bad", None,
            {"type": "hdfs", "settings": {"path": "/x"}})
        assert st == 400
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/bad2", None,
            {"type": "hdfs", "settings": {
                "uri": f"hdfs://{_endpoint(webhdfs)}"}})
        assert st == 400
    finally:
        node.close()
