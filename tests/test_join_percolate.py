"""Parent-join and percolator tests (ref: modules/parent-join,
modules/percolator)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


@pytest.fixture
def qa(node):
    """question/answer join index (the classic parent-join example)."""
    do(node, "PUT", "/qa", body={"mappings": {"properties": {
        "text": {"type": "text"},
        "join": {"type": "join", "relations": {"question": "answer"}},
    }}, "settings": {"index": {"number_of_shards": 1}}})
    docs = [
        ("q1", {"text": "how do I use jax", "join": "question"}),
        ("q2", {"text": "what is a tpu", "join": "question"}),
        ("a1", {"text": "with grad and jit",
                "join": {"name": "answer", "parent": "q1"}}),
        ("a2", {"text": "jax uses xla", "join": {"name": "answer", "parent": "q1"}}),
        ("a3", {"text": "a matrix accelerator",
                "join": {"name": "answer", "parent": "q2"}}),
    ]
    for doc_id, src in docs:
        s, r = node.rest_controller.dispatch("PUT", f"/qa/_doc/{doc_id}",
                                             {"routing": "r"}, src)
        assert s in (200, 201), r
    do(node, "POST", "/qa/_refresh")
    return node


def ids(resp):
    return sorted(h["_id"] for h in resp["hits"]["hits"])


def test_has_child(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"has_child": {
        "type": "answer", "query": {"match": {"text": "jax"}}}}})
    assert ids(r) == ["q1"]
    # both children of q1 and none of q2 match "jax"? a2 has jax, a1 no.
    r2 = do(qa, "POST", "/qa/_search", body={"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}}}}})
    assert ids(r2) == ["q1", "q2"]


def test_has_child_min_children(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}},
        "min_children": 2}}})
    assert ids(r) == ["q1"]


def test_has_child_score_mode(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}},
        "score_mode": "sum"}}})
    by_id = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
    assert by_id["q1"] == 2.0 and by_id["q2"] == 1.0


def test_has_parent(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"has_parent": {
        "parent_type": "question", "query": {"match": {"text": "tpu"}}}}})
    assert ids(r) == ["a3"]


def test_parent_id(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"parent_id": {
        "type": "answer", "id": "q1"}}})
    assert ids(r) == ["a1", "a2"]


def test_join_in_bool(qa):
    r = do(qa, "POST", "/qa/_search", body={"query": {"bool": {
        "must": [{"has_child": {"type": "answer",
                                "query": {"match": {"text": "xla"}}}}]}}})
    assert ids(r) == ["q1"]


def test_join_mapping_validation(qa):
    # unknown relation name rejected
    s, r = qa.rest_controller.dispatch("PUT", "/qa/_doc/bad", None,
                                       {"join": "nonsense"})
    assert s == 400, r
    # child without parent rejected
    s, r = qa.rest_controller.dispatch("PUT", "/qa/_doc/bad2", None,
                                       {"join": {"name": "answer"}})
    assert s == 400, r


def test_join_unmapped(node):
    do(node, "PUT", "/plain", body={})
    node.rest_controller.dispatch("PUT", "/plain/_doc/1", None, {"x": 1})
    do(node, "POST", "/plain/_refresh")
    do(node, "POST", "/plain/_search", body={"query": {"has_child": {
        "type": "answer", "query": {"match_all": {}},
        "ignore_unmapped": True}}})
    s, _ = node.rest_controller.dispatch("POST", "/plain/_search", None,
                                         {"query": {"has_child": {
                                             "type": "answer",
                                             "query": {"match_all": {}}}}})
    assert s == 400


# ----------------------------------------------------------- percolator

@pytest.fixture
def perco(node):
    do(node, "PUT", "/alerts", body={"mappings": {"properties": {
        "query": {"type": "percolator"},
        "message": {"type": "text"},
        "level": {"type": "keyword"},
    }}})
    rules = [
        ("r-error", {"query": {"match": {"message": "error"}}}),
        ("r-crit", {"query": {"bool": {
            "must": [{"match": {"message": "disk"}},
                     {"term": {"level": "critical"}}]}}}),
        ("r-all", {"query": {"match_all": {}}}),
    ]
    for doc_id, src in rules:
        s, r = node.rest_controller.dispatch("PUT", f"/alerts/_doc/{doc_id}",
                                             None, src)
        assert s in (200, 201), r
    do(node, "POST", "/alerts/_refresh")
    return node


def test_percolate_single_doc(perco):
    r = do(perco, "POST", "/alerts/_search", body={"query": {"percolate": {
        "field": "query",
        "document": {"message": "an error occurred", "level": "warn"}}}})
    assert ids(r) == ["r-all", "r-error"]


def test_percolate_bool_rule(perco):
    r = do(perco, "POST", "/alerts/_search", body={"query": {"percolate": {
        "field": "query",
        "document": {"message": "disk full", "level": "critical"}}}})
    assert ids(r) == ["r-all", "r-crit"]


def test_percolate_multiple_docs_slots(perco):
    r = do(perco, "POST", "/alerts/_search", body={"query": {"percolate": {
        "field": "query",
        "documents": [
            {"message": "all is fine"},
            {"message": "error one"},
            {"message": "another error"},
        ]}}})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    assert by_id["r-error"]["fields"]["_percolator_document_slot"] == [1, 2]
    assert by_id["r-all"]["fields"]["_percolator_document_slot"] == [0, 1, 2]


def test_join_child_routes_to_parent_shard(qa):
    # unrouted child docs derive routing from the parent id, so they land
    # on the parent's shard (keeping _update_by_query/_reindex usable on
    # join indices; ES instead rejects with routing_missing_exception)
    s, r = qa.rest_controller.dispatch(
        "PUT", "/qa/_doc/a9", None,
        {"text": "x", "join": {"name": "answer", "parent": "q1"}})
    assert s in (200, 201), r
    idx = qa.indices_service.get("qa")
    assert idx.shard_for("a9", routing="q1") == idx.shard_for("q1")


def test_percolator_rejects_invalid_query(perco):
    s, r = perco.rest_controller.dispatch(
        "PUT", "/alerts/_doc/bad", None,
        {"query": {"no_such_query": {}}})
    assert s == 400, r


def test_percolate_does_not_mutate_mappings(perco):
    before = do(perco, "GET", "/alerts/_mapping")
    do(perco, "POST", "/alerts/_search", body={"query": {"percolate": {
        "field": "query",
        "document": {"message": "error", "brand_new_field": "x"}}}})
    after = do(perco, "GET", "/alerts/_mapping")
    assert before == after
    assert "brand_new_field" not in str(after)


def test_percolate_existing_doc_ref(perco):
    do(perco, "PUT", "/messages", body={})
    perco.rest_controller.dispatch("PUT", "/messages/_doc/m1", None,
                                   {"message": "fatal error in system"})
    do(perco, "POST", "/messages/_refresh")
    r = do(perco, "POST", "/alerts/_search", body={"query": {"percolate": {
        "field": "query", "index": "messages", "id": "m1"}}})
    assert "r-error" in ids(r)


# ---------------------------------------------------------------------------
# children / parent aggregations (ref: modules/parent-join
# join/aggregations — ParentToChildrenAggregator,
# ChildrenToParentAggregator)
# ---------------------------------------------------------------------------


def test_children_aggregation(qa):
    r = do(qa, "POST", "/qa/_search", body={
        "size": 0,
        "query": {"match": {"text": "jax"}},     # parents: q1 only
        "aggs": {"to_answers": {
            "children": {"type": "answer"},
            "aggs": {"words": {"terms": {"field": "join"}}}}}})
    agg = r["aggregations"]["to_answers"]
    # q1 has two answers (a1, a2); q2's answer excluded
    assert agg["doc_count"] == 2
    assert agg["words"]["buckets"][0]["key"] == "answer"
    assert agg["words"]["buckets"][0]["doc_count"] == 2


def test_parent_aggregation(qa):
    r = do(qa, "POST", "/qa/_search", body={
        "size": 0,
        "query": {"match": {"text": "accelerator"}},   # child a3 only
        "aggs": {"to_questions": {
            "parent": {"type": "answer"},
            "aggs": {"cnt": {"value_count": {"field": "_id"}}}}}})
    agg = r["aggregations"]["to_questions"]
    # a3's parent is q2; one parent bucket doc
    assert agg["doc_count"] == 1


def test_children_agg_requires_join_mapping(node):
    do(node, "PUT", "/plain", body={"mappings": {"properties": {
        "t": {"type": "text"}}}})
    do(node, "PUT", "/plain/_doc/1", body={"t": "x"}, expect=201)
    do(node, "POST", "/plain/_refresh")
    status, resp = node.rest_controller.dispatch(
        "POST", "/plain/_search", None,
        {"size": 0, "aggs": {"c": {"children": {"type": "answer"}}}})
    assert status == 400


def test_children_agg_cross_segment_and_deletes(node):
    """Parents and children indexed across refreshes live in different
    segments; the agg joins across them (the two-pass join), and
    deleted children drop out of doc_count."""
    do(node, "PUT", "/qa2", body={"mappings": {"properties": {
        "text": {"type": "text"},
        "join": {"type": "join", "relations": {"question": "answer"}},
    }}})
    s, _ = node.rest_controller.dispatch(
        "PUT", "/qa2/_doc/q1", {"routing": "r"},
        {"text": "the question", "join": "question"})
    assert s == 201
    do(node, "POST", "/qa2/_refresh")          # segment 1: parent only
    for aid in ("a1", "a2"):
        s, _ = node.rest_controller.dispatch(
            "PUT", f"/qa2/_doc/{aid}", {"routing": "r"},
            {"text": "an answer", "join": {"name": "answer",
                                           "parent": "q1"}})
        assert s == 201
    do(node, "POST", "/qa2/_refresh")          # segment 2: children
    r = do(node, "POST", "/qa2/_search", body={
        "size": 0, "query": {"match": {"text": "question"}},
        "aggs": {"c": {"children": {"type": "answer"}}}})
    assert r["aggregations"]["c"]["doc_count"] == 2
    # the mirror direction joins cross-segment too
    r = do(node, "POST", "/qa2/_search", body={
        "size": 0, "query": {"match": {"text": "answer"}},
        "aggs": {"p": {"parent": {"type": "answer"}}}})
    assert r["aggregations"]["p"]["doc_count"] == 1
    # deletes drop from doc_count
    do(node, "DELETE", "/qa2/_doc/a1")
    do(node, "POST", "/qa2/_refresh")
    r = do(node, "POST", "/qa2/_search", body={
        "size": 0, "query": {"match": {"text": "question"}},
        "aggs": {"c": {"children": {"type": "answer"}}}})
    assert r["aggregations"]["c"]["doc_count"] == 1
    # unknown relation type rejected
    status, _ = node.rest_controller.dispatch(
        "POST", "/qa2/_search", None,
        {"size": 0, "aggs": {"c": {"children": {"type": "nope"}}}})
    assert status == 400
