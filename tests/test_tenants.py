"""Tenant-scoped accounting & SLO observability (telemetry/tenants.py +
the noisy_neighbor indicator + the /_tenants surfaces): bounded LRU
cardinality with fold-on-evict, tagging precedence (header > body >
index default), deterministic cluster merge, and noisy-neighbor
attribution naming the tenant in a typed diagnosis.

The chaos paths replay byte-identically from their queue seed."""

import json

import pytest

from test_cluster_node import SimDataCluster, _index_some_docs

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.telemetry import context as telectx
from elasticsearch_tpu.telemetry.context import TraceContext
from elasticsearch_tpu.telemetry.history import MetricsHistory
from elasticsearch_tpu.telemetry.metrics import MetricsRegistry
from elasticsearch_tpu.telemetry.tenants import (
    DEFAULT_TENANT,
    LATENCY_METRIC,
    OVERFLOW_TENANT,
    TENANT_LABEL,
    TenantAccounting,
    merge_tenant_stats,
    render_cat_tenants,
)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _table(max_tenants=64, with_history=True, **kwargs):
    clock = _Clock()
    reg = MetricsRegistry(clock=clock)
    hist = MetricsHistory(reg, clock, interval=10.0) if with_history \
        else None
    return clock, reg, hist, TenantAccounting(
        reg, history=hist, max_tenants=max_tenants, **kwargs)


# ---------------------------------------------------------------------------
# bounded accounting table
# ---------------------------------------------------------------------------


def test_untagged_work_lands_in_default_bucket():
    _, _, _, acct = _table()
    acct.record_search(None, 12.0, shards=3)
    acct.record_indexing("", 256)
    s = acct.stats()
    assert list(s["tenants"]) == [DEFAULT_TENANT]
    e = s["tenants"][DEFAULT_TENANT]
    assert e["search"]["count"] == 1
    assert e["search"]["shard_fanout"] == 3
    assert e["indexing"]["bytes"] == 256


def test_lru_eviction_folds_totals_into_other():
    _, _, _, acct = _table(max_tenants=2)
    acct.record_search("t1", 10.0)
    acct.record_indexing("t1", 100)
    acct.record_search("t2", 20.0)
    acct.record_search("t3", 30.0)   # evicts t1 (least recently active)
    s = acct.stats()
    assert sorted(s["tenants"]) == [OVERFLOW_TENANT, "t2", "t3"]
    assert s["cardinality"]["evictions"] == 1
    other = s["tenants"][OVERFLOW_TENANT]
    # totals are never lost: t1's search + indexing folded by value
    assert other["search"]["count"] == 1
    assert other["search"]["latency"]["count"] == 1
    assert other["indexing"]["bytes"] == 100
    # grand total conserved across the fold
    assert sum(e["search"]["count"]
               for e in s["tenants"].values()) == 3


def test_reserved_buckets_never_count_against_cap():
    _, _, _, acct = _table(max_tenants=2)
    acct.record_search(None, 1.0)           # _default
    acct.record_search("a", 1.0)
    acct.record_search("b", 1.0)
    assert acct.stats()["cardinality"]["evictions"] == 0
    acct.record_search("c", 1.0)            # evicts a -> _other
    live = sorted(acct.stats()["tenants"])
    assert live == [DEFAULT_TENANT, OVERFLOW_TENANT, "b", "c"]
    # reserved buckets survive arbitrary churn
    for i in range(5):
        acct.record_search(f"churn-{i}", 1.0)
    live = acct.active_tenants()
    assert DEFAULT_TENANT in live and OVERFLOW_TENANT in live


def test_eviction_prunes_registry_ring_and_exemplar_slots():
    """The cardinality small-fix pin: an evicted tenant's labeled
    series — including the latency histogram carrying exemplar slots —
    leave the registry AND the history ring, so neither _nodes/stats
    nor ?history=true renders can grow past the cap."""
    clock, reg, hist, acct = _table(max_tenants=1)
    with telectx.activate(TraceContext("trace-ev1")):
        acct.record_search("ev1", 42.0)
    clock.advance(10.0)
    assert hist.advance()   # ring sample holding ev1's series
    assert any(lk and dict(lk).get(TENANT_LABEL) == "ev1"
               for (_n, lk) in hist.samples()[-1][1])
    assert [e for e in reg.exemplars_of(LATENCY_METRIC)
            if e.get("trace_id") == "trace-ev1"], \
        "exemplar slot never recorded"

    acct.record_search("ev2", 7.0)   # evicts ev1
    with reg._lock:
        leaked = [(n, lk) for (n, lk) in reg._metrics
                  if lk and dict(lk).get(TENANT_LABEL) == "ev1"]
    assert leaked == []
    # exemplar slots died with the pruned histogram (not folded)
    assert [e for e in reg.exemplars_of(LATENCY_METRIC)
            if e.get("trace_id") == "trace-ev1"] == []
    # every ring sample scrubbed too
    for _ts, snap in hist.samples():
        assert not any(lk and dict(lk).get(TENANT_LABEL) == "ev1"
                       for (_n, lk) in snap)
    # but the fold preserved the totals in _other
    other = acct.stats()["tenants"][OVERFLOW_TENANT]
    assert other["search"]["count"] == 1
    assert other["search"]["latency"]["sum_ms"] == 42.0


def test_latency_quantiles_are_deterministic_bucket_bounds():
    _, _, _, acct = _table()
    for v in (1.0, 1.0, 1.0, 900.0):
        acct.record_search("q", v)
    lat = acct.stats()["tenants"]["q"]["search"]["latency"]
    # quantiles are bucket upper bounds: p50 covers the 1ms cluster,
    # p99 lands in the bucket holding the 900ms tail observation
    assert lat["p50_ms"] == 1.0
    assert lat["p99_ms"] == 1000.0
    assert lat["count"] == 4


def test_slo_violations_and_budget_burn():
    _, _, _, acct = _table(slo_objectives={"slo-t": 10.0})
    for _ in range(95):
        acct.record_search("slo-t", 5.0)
    for _ in range(5):
        acct.record_search("slo-t", 50.0)
    slo = acct.stats()["tenants"]["slo-t"]["slo"]
    assert slo["objective_ms"] == 10.0
    assert slo["violations"] == 5
    # 1% of 100 requests allowed -> 5 violations = 500% burned
    assert slo["budget_burn_pct"] == 500.0


def test_slo_default_applies_when_no_override():
    _, _, _, acct = _table(slo_default_ms=20.0,
                           slo_objectives={"fast": 5.0})
    assert acct.objective_ms("fast") == 5.0
    assert acct.objective_ms("anyone") == 20.0


# ---------------------------------------------------------------------------
# merge + cat render (ONE shaping impl, two surfaces)
# ---------------------------------------------------------------------------


def _two_node_sections():
    _, _, _, a = _table()
    a.record_search("t1", 2.0, shards=2)
    a.record_search("t1", 200.0)
    a.record_indexing("t1", 50)
    _, _, _, b = _table()
    b.record_search("t1", 2.0)
    b.record_search("t2", 8.0)
    b.record_rejection("t2")
    return {"n-a": a.stats(), "n-b": b.stats()}


def test_merge_sums_counters_and_recomputes_quantiles():
    merged = merge_tenant_stats(_two_node_sections())
    assert merged["nodes"] == ["n-a", "n-b"]
    t1 = merged["tenants"]["t1"]
    assert t1["search"]["count"] == 3
    assert t1["search"]["shard_fanout"] == 2
    assert t1["search"]["latency"]["count"] == 3
    # quantiles recomputed from the SUMMED buckets, not averaged from
    # per-node quantiles: p50 covers the two 2ms observations, p99
    # reaches the bucket holding node a's 200ms one
    assert t1["search"]["latency"]["p50_ms"] == 5.0
    assert t1["search"]["latency"]["p99_ms"] == 500.0
    assert merged["tenants"]["t2"]["indexing"]["rejections"] == 1
    assert merged["cardinality"]["live"] == 2


def test_merge_is_order_independent_and_reports_failures():
    sections = _two_node_sections()
    fwd = merge_tenant_stats(dict(sections))
    rev = merge_tenant_stats(dict(reversed(list(sections.items()))))
    assert json.dumps(fwd, sort_keys=True) == \
        json.dumps(rev, sort_keys=True)
    failed = merge_tenant_stats(sections,
                                [{"node": "n-c", "error": "boom"}])
    assert failed["node_failures"] == [{"node": "n-c", "error": "boom"}]


def test_cat_tenants_renders_merged_rows():
    text = render_cat_tenants(merge_tenant_stats(_two_node_sections()))
    lines = text.splitlines()
    assert lines[0].split()[:2] == ["tenant", "search.count"]
    assert [ln.split()[0] for ln in lines[1:]] == ["t1", "t2"]


# ---------------------------------------------------------------------------
# ambient propagation: context tuple + wire headers
# ---------------------------------------------------------------------------


def test_capture_bind_carries_tenant_across_hop():
    captured = {}

    def probe():
        captured["t"] = telectx.current_tenant()

    with telectx.activate_tenant("hopper"):
        bound = telectx.bind(probe)
    assert telectx.current_tenant() is None
    bound()                       # far side of an executor hop
    assert captured["t"] == "hopper"
    assert telectx.current_tenant() is None   # restored after the hop


def test_wire_headers_round_trip_tenant():
    with telectx.activate_tenant("wire-t"):
        headers = telectx.stamp_task_headers(None)
    assert headers[telectx.TENANT_HEADER] == "wire-t"
    with telectx.incoming(headers):
        assert telectx.current_tenant() == "wire-t"
    assert telectx.current_tenant() is None


# ---------------------------------------------------------------------------
# single-process REST surface
# ---------------------------------------------------------------------------


@pytest.fixture
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, headers=None,
       expect=200):
    status, resp = node.rest_controller.dispatch(
        method, path, params, body, headers=headers)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def _seed(node, index="logs", settings=None):
    do(node, "PUT", f"/{index}", body={"settings": settings or {}})
    do(node, "PUT", f"/{index}/_doc/1",
       body={"body": "quick brown fox"}, expect=201)
    do(node, "POST", f"/{index}/_refresh")


def test_header_tagging_reaches_tenants_stats(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match": {"body": "fox"}}},
       headers={"x-tenant-id": "acme"})
    stats = do(node, "GET", "/_tenants/stats")
    assert stats["nodes"] == [node.node_id]
    assert stats["tenants"]["acme"]["search"]["count"] == 1
    assert stats["tenants"]["acme"]["search"]["latency"]["count"] == 1


def test_tagging_precedence_header_beats_body_beats_index_default(node):
    _seed(node, index="tagged",
          settings={"index.tenant.default": "from-index"})
    # index default applies when nothing stronger is present
    do(node, "POST", "/tagged/_search",
       body={"query": {"match_all": {}}})
    # body tag beats the index default
    do(node, "POST", "/tagged/_search",
       body={"query": {"match_all": {}}, "tenant": "from-body"})
    # header beats both
    do(node, "POST", "/tagged/_search",
       body={"query": {"match_all": {}}, "tenant": "from-body"},
       headers={"X-Tenant-Id": "from-header"})
    t = do(node, "GET", "/_tenants/stats")["tenants"]
    assert t["from-index"]["search"]["count"] == 1
    assert t["from-body"]["search"]["count"] == 1
    assert t["from-header"]["search"]["count"] == 1


def test_untagged_search_charges_default_bucket(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match": {"body": "fox"}}})
    t = do(node, "GET", "/_tenants/stats")["tenants"]
    assert t[DEFAULT_TENANT]["search"]["count"] >= 1


def test_cat_tenants_shares_stats_shaping(node):
    _seed(node)
    do(node, "POST", "/logs/_search",
       body={"query": {"match_all": {}}, "tenant": "cat-t"})
    stats = do(node, "GET", "/_tenants/stats")
    cat = do(node, "GET", "/_cat/tenants")["_cat"]
    lines = cat.splitlines()
    assert lines[0].startswith("tenant")
    # every JSON tenant appears as a cat row with the same count
    for t, e in stats["tenants"].items():
        row = next(ln for ln in lines[1:] if ln.split()[0] == t)
        assert row.split()[1] == str(e["search"]["count"])


def test_slowlog_entries_carry_tenant(node):
    _seed(node, index="slowidx", settings={
        "index.search.slowlog.threshold.query.warn": "0ms"})
    do(node, "POST", "/slowidx/_search",
       body={"query": {"match": {"body": "fox"}}, "tenant": "slow-t"})
    entries = [e for e in node.search_service.slowlog_recent
               if e.get("tenant") == "slow-t"]
    assert entries, list(node.search_service.slowlog_recent)


def test_nodes_stats_renders_tenant_top_n(node):
    _seed(node)
    for _ in range(3):
        do(node, "POST", "/logs/_search",
           body={"query": {"match_all": {}}, "tenant": "busy"})
    do(node, "POST", "/logs/_search",
       body={"query": {"match_all": {}}, "tenant": "idle"})
    ns = do(node, "GET", "/_nodes/stats")
    section = ns["nodes"][node.node_id]["telemetry"]["tenants"]
    assert section["cardinality"]["live"] >= 2
    top = section["top"]
    busy = next(r for r in top if r["tenant"] == "busy")
    assert busy["search_count"] == 3
    assert top[0]["tenant"] == "busy"   # sorted by search count


# ---------------------------------------------------------------------------
# multi-node chaos: fan-out, attribution, replay
# ---------------------------------------------------------------------------


def _tenant_workload(cluster, master):
    cluster.call(master.create_index, "quietidx",
                 number_of_shards=2, number_of_replicas=1,
                 settings={"index.tenant.default": "quiet"})
    cluster.call(master.create_index, "hogidx",
                 number_of_shards=2, number_of_replicas=1,
                 settings={"index.tenant.default": "hog"})
    cluster.run_for(60)
    _index_some_docs(cluster, master, index="quietidx", n=10)
    for _ in range(6):
        cluster.call(master.search, "quietidx",
                     {"tenant": "quiet",
                      "query": {"match": {"body": "fox"}}, "size": 3})
    cluster.call(master.bulk, "hogidx",
                 [{"op": "index", "id": f"h-{i}",
                   "source": {"body": f"hog {i}"}} for i in range(20)])


@pytest.mark.chaos(seed=41)
def test_tenants_stats_fan_out_replays_byte_identical(tmp_path,
                                                      chaos_seed):
    def run(sub):
        c = SimDataCluster(3, tmp_path / sub, seed=chaos_seed)
        m = c.stabilise()
        _tenant_workload(c, m)
        return c.call(m.tenants_stats)

    r1, r2 = run("a"), run("b")
    assert len(r1["nodes"]) == 3 and r1["nodes"] == sorted(r1["nodes"])
    assert {"hog", "quiet"} <= set(r1["tenants"])
    assert r1["tenants"]["quiet"]["search"]["count"] == 6
    assert r1["tenants"]["hog"]["indexing"]["bytes"] > 0
    assert json.dumps(r1, sort_keys=True) == \
        json.dumps(r2, sort_keys=True)


@pytest.mark.chaos(seed=43)
def test_noisy_burst_flips_indicator_and_names_tenant(tmp_path,
                                                      chaos_seed):
    """The acceptance bar: a seeded hog burst flips noisy_neighbor and
    the typed diagnosis names the hog, while the quiet tenant's
    accounting stays clean."""
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    _tenant_workload(c, m)
    baseline = c.call(m.health_report)   # lays the ring's anchor sample
    assert baseline["indicators"]["noisy_neighbor"]["status"] == "green"
    # quiet searches INSIDE the window the final report examines
    for _ in range(4):
        c.call(m.search, "quietidx",
               {"tenant": "quiet", "query": {"match_all": {}},
                "size": 1})
    # seeded burst: shrink the coordinator's indexing-pressure budget
    # so the hog's bulks shed with rejections
    saved = m.indexing_pressure.limit
    m.indexing_pressure.limit = 64
    rejected = 0
    for i in range(8):
        try:
            c.call(m.bulk, "hogidx",
                   [{"op": "index", "id": f"burst-{i}",
                     "source": {"body": "x" * 300}}])
        except Exception:
            rejected += 1
    m.indexing_pressure.limit = saved
    assert rejected == 8
    c.run_for(11)                 # cross the next history boundary
    report = c.call(m.health_report)
    noisy = report["indicators"]["noisy_neighbor"]
    assert noisy["status"] in ("yellow", "red")
    assert noisy["diagnosis"][0]["id"] == \
        "noisy_neighbor:dominant_tenant"
    named = {r for d in noisy["diagnosis"]
             for r in d["affected_resources"]}
    assert named == {"hog"}
    # quiet tenant's accounting untouched by the hog's shed load
    merged = c.call(m.tenants_stats)
    assert merged["tenants"]["quiet"]["indexing"]["rejections"] == 0
    assert merged["tenants"]["quiet"]["search"]["failed"] == 0
    assert merged["tenants"]["hog"]["indexing"]["rejections"] == 8


@pytest.mark.chaos(seed=47)
def test_untagged_cluster_work_lands_in_default(tmp_path, chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    c.call(m.create_index, "plain", number_of_shards=2,
           number_of_replicas=1)
    c.run_for(60)
    _index_some_docs(c, m, index="plain", n=8)
    c.call(m.search, "plain", {"query": {"match_all": {}}, "size": 2})
    merged = c.call(m.tenants_stats)
    assert DEFAULT_TENANT in merged["tenants"]
    assert merged["tenants"][DEFAULT_TENANT]["search"]["count"] >= 1


@pytest.mark.chaos(seed=53)
def test_cap_overflow_preserves_totals_across_fan_out(tmp_path,
                                                      chaos_seed):
    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    c.call(m.create_index, "caps", number_of_shards=1,
           number_of_replicas=0)
    c.run_for(60)
    _index_some_docs(c, m, index="caps", n=5)
    for cn in c.cluster_nodes.values():
        cn.telemetry.tenants.max_tenants = 2
    for i in range(5):
        c.call(m.search, "caps",
               {"tenant": f"cap-{i}", "query": {"match_all": {}},
                "size": 1})
    merged = c.call(m.tenants_stats)
    # coordinator-side: 5 tenants squeezed through a cap of 2 — the
    # evicted ones folded into _other, totals conserved
    total = sum(e["search"]["count"]
                for t, e in merged["tenants"].items()
                if t.startswith("cap-") or t == OVERFLOW_TENANT)
    assert total == 5
    assert merged["cardinality"]["evictions"] >= 3
    assert OVERFLOW_TENANT in merged["tenants"]
