"""Circuit breaker + BigArrays accounting tests (model: the reference's
MockBigArrays assert-all-released discipline, SURVEY.md §5.2)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import CircuitBreakingException
from elasticsearch_tpu.utils.bigarrays import BigArrays
from elasticsearch_tpu.utils.breaker import (
    CircuitBreaker,
    HierarchyCircuitBreakerService,
)


def test_child_breaker_trips():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1000, request_limit_bytes=100)
    br = svc.get_breaker(CircuitBreaker.REQUEST)
    br.add_estimate_bytes_and_maybe_break(80, "a")
    with pytest.raises(CircuitBreakingException):
        br.add_estimate_bytes_and_maybe_break(50, "b")
    # failed reservation must not leak accounting
    assert br.used == 80
    assert br.trip_count == 1


def test_parent_breaker_trips_across_children():
    svc = HierarchyCircuitBreakerService(
        total_limit_bytes=150, request_limit_bytes=100, fielddata_limit_bytes=100)
    svc.get_breaker(CircuitBreaker.REQUEST).add_estimate_bytes_and_maybe_break(90, "r")
    with pytest.raises(CircuitBreakingException):
        svc.get_breaker(CircuitBreaker.FIELDDATA).add_estimate_bytes_and_maybe_break(90, "f")
    # the child that tripped the parent must roll back its reservation
    assert svc.get_breaker(CircuitBreaker.FIELDDATA).used == 0


def test_bigarrays_accounts_and_releases():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=10_000, request_limit_bytes=5000)
    ba = BigArrays(svc)
    br = svc.get_breaker(CircuitBreaker.REQUEST)
    with ba.new_array((10, 10), np.float32, "scores") as acc:
        assert acc.array.shape == (10, 10)
        assert br.used == 400
    assert br.used == 0


def test_bigarrays_breaks_on_huge_alloc():
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1000, request_limit_bytes=500)
    ba = BigArrays(svc)
    with pytest.raises(CircuitBreakingException):
        ba.new_array((1000,), np.float64, "huge")
    assert svc.get_breaker(CircuitBreaker.REQUEST).used == 0
