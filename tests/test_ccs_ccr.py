"""Cross-cluster search + replication tests: two REAL nodes over HTTP
(model: qa/multi-cluster-search and x-pack CCR IT discipline — a live
leader and follower cluster wired via remote-cluster settings)."""

import time

import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture()
def clusters(tmp_path):
    """(local_node, remote_node) with remote registered as 'remote1'."""
    local = Node(data_path=str(tmp_path / "local"))
    remote = Node(data_path=str(tmp_path / "remote"))
    rport = remote.start(0)
    local.remote_cluster_service.register("remote1",
                                          [f"127.0.0.1:{rport}"])
    yield local, remote
    local.close()
    remote.close()


def call(node, method, path, body=None, expect=200, **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, r
    return r


def _seed(node, index, docs, mappings=None):
    node.indices_service.create_index(index, {}, mappings or {
        "properties": {"title": {"type": "text"},
                       "rank": {"type": "long"}}})
    idx = node.indices_service.get(index)
    for i, d in enumerate(docs):
        idx.index_doc(str(i), d)
    idx.refresh()
    return idx


def test_remote_info_and_settings(clusters):
    local, remote = clusters
    r = call(local, "GET", "/_remote/info")
    assert r["remote1"]["connected"] is True
    # registration via the settings API works too
    call(local, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote.second.seeds": r["remote1"]["seeds"]}})
    r = call(local, "GET", "/_remote/info")
    assert "second" in r


def test_ccs_merges_hits(clusters):
    local, remote = clusters
    _seed(local, "books", [{"title": "local one", "rank": 10},
                           {"title": "local two", "rank": 30}])
    _seed(remote, "books", [{"title": "remote one", "rank": 20},
                            {"title": "remote two", "rank": 40}])
    r = call(local, "POST", "/books,remote1:books/_search", {
        "size": 10, "sort": [{"rank": {"order": "desc"}}]})
    assert r["hits"]["total"]["value"] == 4
    ranks = [h["sort"][0] for h in r["hits"]["hits"]]
    assert ranks == [40, 30, 20, 10]
    indices = [h["_index"] for h in r["hits"]["hits"]]
    assert indices == ["remote1:books", "books", "remote1:books", "books"]


def test_ccs_remote_only_by_score(clusters):
    local, remote = clusters
    _seed(remote, "docs", [{"title": "alpha match match", "rank": 1},
                           {"title": "alpha", "rank": 2}])
    r = call(local, "POST", "/remote1:docs/_search", {
        "query": {"match": {"title": {"query": "match"}}}})
    assert r["hits"]["total"]["value"] == 1
    assert r["hits"]["hits"][0]["_index"] == "remote1:docs"


def test_ccr_follow_and_tail(clusters):
    local, remote = clusters
    ridx = _seed(remote, "leader", [{"title": "first", "rank": 1}])
    r = call(local, "PUT", "/follower/_ccr/follow", {
        "remote_cluster": "remote1", "leader_index": "leader"})
    assert r["index_following_started"] is True
    got = local.search_service.search("follower", {"size": 10})
    assert got["hits"]["total"]["value"] == 1

    # new leader writes flow to the follower via the poll loop
    ridx.index_doc("n1", {"title": "second", "rank": 2})
    ridx.refresh()
    deadline = time.time() + 5
    while time.time() < deadline:
        local.ccr_service.sync("follower")
        got = local.search_service.search("follower", {"size": 10})
        if got["hits"]["total"]["value"] == 2:
            break
        time.sleep(0.1)
    assert got["hits"]["total"]["value"] == 2

    # deletes replicate too
    ridx.delete_doc("0")
    ridx.refresh()
    deadline = time.time() + 5
    while time.time() < deadline:
        local.ccr_service.sync("follower")
        got = local.search_service.search("follower", {"size": 10})
        if got["hits"]["total"]["value"] == 1:
            break
        time.sleep(0.1)
    assert got["hits"]["total"]["value"] == 1
    assert got["hits"]["hits"][0]["_source"]["title"] == "second"

    stats = call(local, "GET", "/_ccr/stats")
    shard_stats = stats["follow_stats"]["indices"][0]["shards"][0]
    assert shard_stats["operations_written"] >= 3


def test_ccr_pause_resume_unfollow(clusters):
    local, remote = clusters
    ridx = _seed(remote, "leader", [{"title": "a", "rank": 1}])
    call(local, "PUT", "/follower/_ccr/follow", {
        "remote_cluster": "remote1", "leader_index": "leader"})
    call(local, "POST", "/follower/_ccr/pause_follow")
    ridx.index_doc("x", {"title": "b", "rank": 2})
    ridx.refresh()
    assert local.ccr_service.sync("follower") == 0     # paused
    call(local, "POST", "/follower/_ccr/resume_follow")
    got = local.search_service.search("follower", {"size": 10})
    assert got["hits"]["total"]["value"] == 2
    info = call(local, "GET", "/follower/_ccr/info")
    assert info["follower_indices"][0]["status"] == "active"
    call(local, "POST", "/follower/_ccr/unfollow")
    call(local, "GET", "/follower/_ccr/info", expect=404)


def test_ccr_auto_follow(clusters):
    local, remote = clusters
    call(local, "PUT", "/_ccr/auto_follow/metrics-pattern", {
        "remote_cluster": "remote1",
        "leader_index_patterns": ["metrics-*"],
        "follow_index_pattern": "copy-{{leader_index}}"})
    _seed(remote, "metrics-2026", [{"title": "m", "rank": 1}])
    local.ccr_service.scan_auto_follow()
    assert "copy-metrics-2026" in local.ccr_service.tasks
    got = local.search_service.search("copy-metrics-2026", {"size": 10})
    assert got["hits"]["total"]["value"] == 1
    r = call(local, "GET", "/_ccr/auto_follow")
    assert r["patterns"][0]["name"] == "metrics-pattern"
    call(local, "DELETE", "/_ccr/auto_follow/metrics-pattern")
    call(local, "GET", "/_ccr/auto_follow/metrics-pattern", expect=404)


def test_remote_settings_partial_update_keeps_connection(clusters):
    local, remote = clusters
    info = call(local, "GET", "/_remote/info")
    call(local, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote.remote1.skip_unavailable": True}})
    info2 = call(local, "GET", "/_remote/info")
    assert "remote1" in info2 and info2["remote1"]["connected"]
    # explicit null removes the connection
    call(local, "PUT", "/_cluster/settings", {"persistent": {
        "cluster.remote.remote1.seeds": None}})
    assert "remote1" not in call(local, "GET", "/_remote/info")


# ---------------------------------------------------------------------------
# Proxy connection mode (ref: transport/ProxyConnectionStrategy.java:49)
# ---------------------------------------------------------------------------

def test_proxy_mode_remote_search(tmp_path):
    """cluster.remote.*.mode=proxy connects through ONE address with a
    pooled persistent-connection client (no sniffing) and serves CCS."""
    local = Node(data_path=str(tmp_path / "local"))
    remote = Node(data_path=str(tmp_path / "remote"))
    try:
        rport = remote.start(0)
        remote.indices_service.create_index("prodx", {}, None)
        ridx = remote.indices_service.get("prodx")
        for i in range(4):
            ridx.index_doc(str(i), {"title": f"doc {i}"})
        ridx.refresh()
        call(local, "PUT", "/_cluster/settings", {
            "persistent": {"cluster": {"remote": {"prox": {
                "mode": "proxy",
                "proxy_address": f"127.0.0.1:{rport}",
                "proxy_socket_connections": 3}}}}})
        from elasticsearch_tpu.transport.remote import (
            ProxyRemoteClusterClient)
        client = local.remote_cluster_service.get_client("prox")
        assert isinstance(client, ProxyRemoteClusterClient)
        r = call(local, "POST", "/prox:prodx/_search",
                 {"query": {"match_all": {}}, "size": 10})
        assert r["hits"]["total"]["value"] == 4
        assert all(h["_index"] == "prox:prodx"
                   for h in r["hits"]["hits"])
        # repeated requests reuse pooled sockets (bounded by the
        # configured pool size)
        for _ in range(5):
            call(local, "POST", "/prox:prodx/_search",
                 {"query": {"match_all": {}}, "size": 1})
        stats = client.pool_stats()
        assert stats["max"] == 3
        assert 1 <= stats["created"] <= 3
        info = call(local, "GET", "/_remote/info")
        assert info["prox"]["mode"] == "proxy"
        assert info["prox"]["proxy_address"] == f"127.0.0.1:{rport}"
        assert info["prox"]["connected"] is True
    finally:
        local.close()
        remote.close()


def test_proxy_mode_redials_dropped_connections(tmp_path):
    """A stale pooled socket (server restarted) is re-dialed
    transparently instead of failing the request."""
    local = Node(data_path=str(tmp_path / "local"))
    remote = Node(data_path=str(tmp_path / "remote"))
    remote2 = None
    try:
        rport = remote.start(0)
        remote.indices_service.create_index("i1", {}, None)
        remote.indices_service.get("i1").index_doc("1", {"a": 1})
        remote.indices_service.get("i1").refresh()
        local.remote_cluster_service.apply_settings({
            "cluster": {"remote": {"p": {
                "mode": "proxy",
                "proxy_address": f"127.0.0.1:{rport}"}}}})
        client = local.remote_cluster_service.get_client("p")
        assert client.request("GET", "/")["cluster_name"]
        # kill the remote; the pooled socket is now dead
        remote.close()
        import pytest as _pytest
        from elasticsearch_tpu.common.errors import (
            ElasticsearchTpuException)
        with _pytest.raises(ElasticsearchTpuException):
            client.request("GET", "/")
        # bring a NEW server up on the same port (LB failover shape)
        remote2 = Node(data_path=str(tmp_path / "remote2"))
        try:
            remote2.start(rport)
        except OSError:
            _pytest.skip("port was reclaimed by the OS")
        assert client.request("GET", "/")["cluster_name"]
    finally:
        local.close()
        if remote2 is not None:
            remote2.close()
