"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (mirrors the reference's
InternalTestCluster strategy of booting multiple nodes in one JVM, ref:
test/framework/.../InternalTestCluster.java): sharding/collective code is
exercised without TPU hardware. Must set env vars before jax import.
"""

import os

# override, not setdefault: the harness presets JAX_PLATFORMS=axon (TPU)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
