"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (mirrors the reference's
InternalTestCluster strategy of booting multiple nodes in one JVM, ref:
test/framework/.../InternalTestCluster.java): sharding/collective code is
exercised without TPU hardware.

Note: the harness's axon site hook (PYTHONPATH=/root/.axon_site) re-forces
JAX_PLATFORMS=axon during jax import, so setting the env var is NOT enough —
the platform must be pinned via jax.config AFTER import (XLA_FLAGS must
still be set BEFORE import for the host-device count to apply).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devices = jax.devices()
    assert devices[0].platform == "cpu" and len(devices) == 8, devices
