"""Test harness configuration.

Tests run JAX on a virtual 8-device CPU mesh (mirrors the reference's
InternalTestCluster strategy of booting multiple nodes in one JVM, ref:
test/framework/.../InternalTestCluster.java): sharding/collective code is
exercised without TPU hardware.

Note: the harness's axon site hook (PYTHONPATH=/root/.axon_site) re-forces
JAX_PLATFORMS=axon during jax import, so setting the env var is NOT enough —
the platform must be pinned via jax.config AFTER import (XLA_FLAGS must
still be set BEFORE import for the host-device count to apply).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--chaos-seed", action="store", default=None, type=int,
        help="override the fault-injection seed for @pytest.mark.chaos "
             "tests (replay a red chaos run from its logged seed)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos(seed=N): seeded fault-injection test; the active seed is "
        "echoed on failure so any red run replays with --chaos-seed=N")


@pytest.fixture
def chaos_seed(request):
    """The fault-injection seed for this test: --chaos-seed wins,
    otherwise the @pytest.mark.chaos(seed=...) default. The chosen seed
    is stashed on the test item so a failure report echoes it."""
    override = request.config.getoption("--chaos-seed")
    marker = request.node.get_closest_marker("chaos")
    seed = override if override is not None else (
        marker.kwargs.get("seed", 0) if marker else 0)
    request.node._chaos_seed_used = seed
    return seed


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if rep.when == "call" and rep.failed and \
            item.get_closest_marker("chaos") is not None:
        seed = getattr(item, "_chaos_seed_used", "?")
        rep.sections.append(
            ("chaos fault injection",
             f"seeded chaos run failed; replay deterministically with: "
             f"pytest {item.nodeid} --chaos-seed={seed}"))
        if hasattr(rep.longrepr, "addsection"):
            rep.longrepr.addsection(
                "chaos seed", f"replay with --chaos-seed={seed}")


@pytest.fixture(autouse=True)
def _span_leak_guard():
    """Telemetry hygiene: fail any test that starts a trace span and
    never finishes it. Spans already open before the test (e.g. a
    background service of a long-lived node from another fixture) are
    excluded — only spans OPENED during this test count as leaks."""
    from elasticsearch_tpu.telemetry import tracing
    before = tracing.open_span_keys()
    yield
    leaked = tracing.open_span_keys() - before
    if leaked:
        # wall-clock transports may still be completing an RPC; give
        # in-flight handlers one beat before calling it a leak
        import time as _time
        _time.sleep(0.2)
        leaked = tracing.open_span_keys() - before
    assert not leaked, (
        "telemetry spans left open at teardown (started, never "
        f"finished): {sorted(k[3] for k in leaked)}")


@pytest.fixture(autouse=True)
def _task_leak_guard():
    """Task hygiene (mirror of the span-leak guard): fail any test that
    registers a task in a TaskManager and never unregisters it. Tasks
    already live before the test (e.g. a background service of a
    long-lived node from another fixture) are excluded — only tasks
    REGISTERED during this test count as leaks."""
    from elasticsearch_tpu.transport import tasks as _tasks
    before = _tasks.open_task_keys()
    yield
    leaked = _tasks.open_task_keys() - before
    if leaked:
        # wall-clock transports/threads may still be completing a
        # request; give in-flight handlers one beat before calling it
        import time as _time
        _time.sleep(0.2)
        leaked = _tasks.open_task_keys() - before
    assert not leaked, (
        "tasks left registered at teardown (registered, never "
        f"unregistered): {sorted((k[0], k[2]) for k in leaked)}")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devices = jax.devices()
    assert devices[0].platform == "cpu" and len(devices) == 8, devices
