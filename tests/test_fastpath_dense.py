"""Dense-patch essential lane (ops/fastpath.bm25_essential_dense_topk_batch):
identical certified outputs to the binary-search patch lane and to the
full exact v1 kernel, honest ok=0 when the certificate can't close.

The dense lane exists for the degraded-tunnel serving regime, where the
binary-search patch's ~170 dependent gathers cost more than the full
kernel they replace (BASELINE.md round-5 notes); its contract is the
binary lane's, so the tests drive both through the same splits.
"""

import numpy as np
import pytest

import jax

from elasticsearch_tpu.ops import fastpath as fp

BLOCK = 128
K1, B = 1.2, 0.75


def build_segment(rng, n_docs=600, n_hot=2, n_rare=3):
    """Hot terms (high df, low idf — the NE side of a MaxScore split)
    plus rare terms; block layout like index/segment.py."""
    blocks_d, blocks_t = [], []
    tbs, nb, dfs = [], [], []
    next_block = 0
    terms = []
    for i in range(n_hot + n_rare):
        df = int(rng.integers(int(n_docs * 0.6), n_docs)) if i < n_hot \
            else int(rng.integers(8, 40))
        docs = np.sort(rng.choice(n_docs, size=df,
                                  replace=False)).astype(np.int32)
        tfs = rng.integers(1, 6, size=df).astype(np.float32)
        nblk = (df + BLOCK - 1) // BLOCK
        tbs.append(next_block)
        nb.append(nblk)
        dfs.append(df)
        next_block += nblk
        pad = nblk * BLOCK - df
        blocks_d.append(np.concatenate(
            [docs, np.zeros(pad, np.int32)]).reshape(nblk, BLOCK))
        blocks_t.append(np.concatenate(
            [tfs, np.zeros(pad, np.float32)]).reshape(nblk, BLOCK))
        terms.append((docs, tfs))
    blocks_d.append(np.zeros((1, BLOCK), np.int32))
    blocks_t.append(np.zeros((1, BLOCK), np.float32))
    bd = np.concatenate(blocks_d)
    bt = np.concatenate(blocks_t)
    lens = rng.integers(5, 80, size=n_docs).astype(np.float32)
    return dict(bd=bd, bt=bt, tbs=np.asarray(tbs), nb=np.asarray(nb),
                dfs=np.asarray(dfs), zero_block=bd.shape[0] - 1,
                lens=lens, avg=float(lens.mean()), terms=terms,
                flat_d=bd.reshape(-1), flat_t=bt.reshape(-1),
                n_docs=n_docs, n_hot=n_hot)


def idf_of(seg, t):
    n = seg["n_docs"]
    df = seg["dfs"][t]
    return float(np.log1p((n - df + 0.5) / (df + 0.5)))


def dense_table(seg):
    """[H, ND] exact tf rows for the hot terms (float16: counts < 2048)."""
    h = seg["n_hot"]
    dense = np.zeros((h, seg["n_docs"]), np.float16)
    for t in range(h):
        docs, tfs = seg["terms"][t]
        dense[t, docs] = tfs
    return dense


def _bucket_for(seg, terms):
    need = int(sum(seg["nb"][t] for t in terms))
    nbk = 64
    while nbk < need:
        nbk *= 2
    return nbk


def full_v1(seg, ess_and_ne, k, masks=None, mask_id=0):
    """Reference: the exact full kernel over ALL the query's terms."""
    q = 1
    nbk = _bucket_for(seg, ess_and_ne)
    sel = np.full((q, nbk), seg["zero_block"], np.int32)
    ws = np.zeros((q, nbk), np.float64)
    pos = 0
    for t in ess_and_ne:
        cnt = int(seg["nb"][t])
        start = int(seg["tbs"][t])
        sel[0, pos:pos + cnt] = np.arange(start, start + cnt)
        ws[0, pos:pos + cnt] = idf_of(seg, t)
        pos += cnt
    if masks is None:
        masks = np.ones((fp.F_SLOTS, seg["n_docs"]), bool)
    out = np.asarray(fp.bm25_topk_total_batch(
        seg["bd"], seg["bt"], sel, ws, seg["lens"], masks,
        np.full(q, mask_id, np.int32), np.float64(seg["avg"]),
        K1, B, k))
    vals = out[0, :k]
    ids = out[0, k:2 * k].astype(np.int32)
    order = np.lexsort((ids, -vals))
    return vals[order], ids[order], int(out[0, 2 * k:].astype(np.int32)[0])


def run_lanes(seg, ess, ne, ne_bound, k, masks=None, mask_id=0):
    """(binary_out, dense_out) for the same essential/NE split."""
    q = 1
    nbk = _bucket_for(seg, ess)
    sel = np.full((q, nbk), seg["zero_block"], np.int32)
    ws = np.zeros((q, nbk), np.float64)
    pos = 0
    for t in ess:
        cnt = int(seg["nb"][t])
        start = int(seg["tbs"][t])
        sel[0, pos:pos + cnt] = np.arange(start, start + cnt)
        ws[0, pos:pos + cnt] = idf_of(seg, t)
        pos += cnt
    ne_start = np.zeros((q, fp.NE_SLOTS), np.int32)
    ne_len = np.zeros((q, fp.NE_SLOTS), np.int32)
    ne_row = np.full((q, fp.NE_SLOTS), -1, np.int32)
    ne_idf = np.zeros((q, fp.NE_SLOTS), np.float64)
    for i, t in enumerate(ne):
        ne_start[0, i] = int(seg["tbs"][t]) * BLOCK
        ne_len[0, i] = int(seg["dfs"][t])
        ne_row[0, i] = t            # dense rows are the hot-term index
        ne_idf[0, i] = idf_of(seg, t)
    nbound = np.full(q, ne_bound, np.float64)
    if masks is None:
        masks = np.ones((fp.F_SLOTS, seg["n_docs"]), bool)
    mids = np.full(q, mask_id, np.int32)
    binary = np.asarray(fp.bm25_essential_topk_batch(
        seg["bd"], seg["bt"], seg["flat_d"], seg["flat_t"], sel, ws,
        seg["lens"], masks, mids, ne_start, ne_len, ne_idf, nbound,
        np.float64(seg["avg"]), K1, B, k))
    dense = np.asarray(fp.bm25_essential_dense_topk_batch(
        seg["bd"], seg["bt"], dense_table(seg), sel, ws,
        seg["lens"], masks, mids, ne_row, ne_idf, nbound,
        np.float64(seg["avg"]), K1, B, k))
    return binary, dense


def unpack(out, k):
    vals = out[0, :k]
    ids = out[0, k:2 * k].astype(np.int32)
    ok = int(out[0, 2 * k:].astype(np.int32)[0])
    return vals, ids, ok


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_dense_matches_binary_and_full(seed):
    rng = np.random.default_rng(seed)
    seg = build_segment(rng)
    k = 10
    query = [2, 3, 0]       # two rare + one hot
    ess, ne = [2, 3], [0]
    # a true Σ maxc_ne bound for term 0
    docs, tfs = seg["terms"][0]
    norm_min = K1 * (1 - B + B * seg["lens"][docs].min() / seg["avg"])
    bound = idf_of(seg, 0) * float(
        (tfs / (tfs + norm_min)).max()) + 1e-9
    fv, fi, _ftot = full_v1(seg, query, k)
    binary, dense = run_lanes(seg, ess, ne, bound, k)
    bv, bi, bok = unpack(binary, k)
    dv, di, dok = unpack(dense, k)
    assert bok == dok
    np.testing.assert_array_equal(bi, di)
    np.testing.assert_allclose(bv, dv, rtol=0, atol=0)
    if dok:
        np.testing.assert_array_equal(di, fi)
        np.testing.assert_allclose(dv, fv, rtol=0, atol=0)


def test_dense_unused_slots_are_inert():
    rng = np.random.default_rng(7)
    seg = build_segment(rng)
    k = 5
    # no NE terms at all: both lanes degenerate to the essential union
    binary, dense = run_lanes(seg, [2, 3], [], 0.0, k)
    np.testing.assert_array_equal(binary, dense)


def test_dense_respects_filter_mask():
    rng = np.random.default_rng(11)
    seg = build_segment(rng)
    k = 5
    masks = np.ones((fp.F_SLOTS, seg["n_docs"]), bool)
    masks[3] = False
    masks[3, : seg["n_docs"] // 2] = True      # keep low half only
    docs, tfs = seg["terms"][0]
    bound = idf_of(seg, 0) * 1.0 + 1e-9
    binary, dense = run_lanes(seg, [2, 3], [0], bound, k,
                              masks=masks, mask_id=3)
    bv, bi, bok = unpack(binary, k)
    dv, di, dok = unpack(dense, k)
    assert bok == dok
    np.testing.assert_array_equal(bi, di)
    finite = np.isfinite(dv)
    assert np.all(di[finite] < seg["n_docs"] // 2)


def test_dense_certificate_refuses_when_bound_wide():
    """A huge Σ maxc_ne makes overflow_bound beat the kth — both lanes
    must refuse (ok=0) instead of certifying a possibly-wrong top-k.
    The essential union must exceed CAND docs (otherwise every match is
    a candidate and the certificate closes trivially — correctly)."""
    rng = np.random.default_rng(13)
    nd = int(fp.CAND * 1.5)
    seg = build_segment(rng, n_docs=nd, n_hot=2, n_rare=1)
    # make hot term 0's df exceed CAND so phase 1 overflows (the
    # adaptive c = min(CAND, lanes-1) must saturate at CAND)
    while seg["dfs"][0] <= fp.CAND:
        seg = build_segment(np.random.default_rng(
            int(rng.integers(1 << 30))), n_docs=nd, n_hot=2, n_rare=1)
    k = 10
    binary, dense = run_lanes(seg, [0], [1], 1e6, k)
    _bv, _bi, bok = unpack(binary, k)
    _dv, _di, dok = unpack(dense, k)
    assert bok == 0 and dok == 0
