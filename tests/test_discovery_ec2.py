"""discovery-ec2 seed provider (ref: plugins/discovery-ec2/.../
AwsEc2SeedHostsProvider.java) against an in-process DescribeInstances
fixture that verifies the SigV4-signed Query-API request shape."""

import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qsl

import pytest

from elasticsearch_tpu.cluster import discovery
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.plugins import main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DESCRIBE_XML = """<?xml version="1.0" encoding="UTF-8"?>
<DescribeInstancesResponse xmlns="http://ec2.amazonaws.com/doc/2016-11-15/">
 <reservationSet><item><instancesSet>
  <item>
   <instanceId>i-0001</instanceId>
   <privateIpAddress>10.0.0.11</privateIpAddress>
   <ipAddress>54.1.2.3</ipAddress>
  </item>
  <item>
   <instanceId>i-0002</instanceId>
   <privateIpAddress>10.0.0.12</privateIpAddress>
   <ipAddress>54.1.2.4</ipAddress>
  </item>
 </instancesSet></item></reservationSet>
</DescribeInstancesResponse>"""


class _Ec2Fixture(BaseHTTPRequestHandler):
    requests = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        ln = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(ln).decode()
        _Ec2Fixture.requests.append(
            (dict(parse_qsl(body)), dict(self.headers)))
        data = DESCRIBE_XML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/xml")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture()
def ec2(tmp_path):
    srv = HTTPServer(("127.0.0.1", 0), _Ec2Fixture)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    _Ec2Fixture.requests.clear()
    pd = str(tmp_path / "plugins")
    plugin_cli(["install",
                os.path.join(REPO_ROOT, "plugins_src", "discovery_ec2"),
                "--plugins-dir", pd])
    from elasticsearch_tpu.plugins import PluginsService
    svc = PluginsService(pd)
    svc.load_all()
    yield srv
    srv.shutdown()
    discovery.PLUGIN_SEED_PROVIDERS.pop("ec2", None)


def test_ec2_seed_hosts_with_tag_filters(ec2):
    settings = Settings.from_dict({
        "discovery": {"ec2": {
            "endpoint": f"http://127.0.0.1:{ec2.server_address[1]}/",
            "access_key": "AKIDEXAMPLE", "secret_key": "s3cr3t",
            "tag": {"role": "es-node"},
            "port": 9377}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    assert [(n.host, n.port) for n in seeds] == \
        [("10.0.0.11", 9377), ("10.0.0.12", 9377)]
    # the fixture saw a real SigV4-signed DescribeInstances request
    params, headers = _Ec2Fixture.requests[0]
    assert params["Action"] == "DescribeInstances"
    assert params["Filter.1.Name"] == "instance-state-name"
    assert params["Filter.2.Name"] == "tag:role"
    assert params["Filter.2.Value.1"] == "es-node"
    auth = headers.get("Authorization", "")
    assert auth.startswith("AWS4-HMAC-SHA256")
    assert "Credential=AKIDEXAMPLE/" in auth and "/ec2/aws4_request" in auth


def test_ec2_public_ip_and_unreachable(ec2):
    settings = Settings.from_dict({
        "discovery": {"ec2": {
            "endpoint": f"http://127.0.0.1:{ec2.server_address[1]}/",
            "host_type": "public_ip"}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    assert [n.host for n in seeds] == ["54.1.2.3", "54.1.2.4"]
    # unreachable endpoint → empty, never a crash
    bad = Settings.from_dict({
        "discovery": {"ec2": {"endpoint": "http://127.0.0.1:1/"}}})
    assert discovery.resolve_seed_hosts(settings=bad) == []


def test_merges_with_settings_seeds(ec2):
    settings = Settings.from_dict({
        "discovery": {
            "seed_hosts": "192.168.0.5:9300",
            "ec2": {"endpoint":
                    f"http://127.0.0.1:{ec2.server_address[1]}/"}}})
    seeds = discovery.resolve_seed_hosts(settings=settings)
    assert [(n.host, n.port) for n in seeds] == [
        ("192.168.0.5", 9300), ("10.0.0.11", 9300), ("10.0.0.12", 9300)]
