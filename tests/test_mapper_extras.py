"""Extra field-type tests: range types, wildcard, flattened-era extras,
constant_keyword, rank_feature(s), search_as_you_type, token_count, murmur3
(model: the reference's per-mapper test classes under modules/mapper-extras
and x-pack mapper plugins)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import MapperParsingException
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops.device import DeviceSegment
from elasticsearch_tpu.search.context import SegmentContext, ShardStats
from elasticsearch_tpu.search.queries import parse_query

MAPPINGS = {
    "properties": {
        "age_range": {"type": "integer_range"},
        "when": {"type": "date_range"},
        "code": {"type": "wildcard"},
        "env": {"type": "constant_keyword", "value": "prod"},
        "pagerank": {"type": "rank_feature"},
        "inverse_rank": {"type": "rank_feature",
                         "positive_score_impact": False},
        "topics": {"type": "rank_features"},
        "title": {"type": "search_as_you_type"},
        "title_len": {"type": "token_count", "analyzer": "standard"},
        "h": {"type": "murmur3"},
    }
}

DOCS = [
    {"age_range": {"gte": 10, "lte": 20}, "code": "alpha-123",
     "pagerank": 10.0, "inverse_rank": 1.0, "topics": {"sports": 20.0},
     "title": "quick brown fox", "title_len": "one two three", "h": "a"},
    {"age_range": {"gte": 15, "lte": 30}, "code": "beta-456",
     "pagerank": 2.0, "inverse_rank": 5.0, "topics": {"politics": 3.0},
     "title": "quick brawl", "title_len": "one two", "h": "b",
     "env": "prod"},
    {"age_range": {"gte": 40, "lte": 50}, "code": "alpha-789",
     "pagerank": 5.0, "topics": {"sports": 1.0, "politics": 8.0},
     "title": "slow snail", "title_len": "one", "h": "a"},
]


@pytest.fixture(scope="module")
def ctx():
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i, d in enumerate(DOCS):
        w.add(svc.parse(str(i), d))
    seg = w.build("s0")
    return SegmentContext(seg, DeviceSegment(seg), svc, ShardStats([seg]))


def run(ctx, query_dict):
    q = parse_query(query_dict)
    scores, mask = q.execute(ctx)
    return (np.asarray(scores)[: ctx.segment.n_docs],
            np.asarray(mask)[: ctx.segment.n_docs])


def matching(ctx, query_dict):
    _, mask = run(ctx, query_dict)
    return set(np.nonzero(mask)[0].tolist())


# ---- range fields ----

def test_range_field_intersects(ctx):
    assert matching(ctx, {"range": {"age_range": {
        "gte": 18, "lte": 25}}}) == {0, 1}


def test_range_field_within(ctx):
    assert matching(ctx, {"range": {"age_range": {
        "gte": 5, "lte": 35, "relation": "within"}}}) == {0, 1}


def test_range_field_contains(ctx):
    assert matching(ctx, {"range": {"age_range": {
        "gte": 16, "lte": 18, "relation": "contains"}}}) == {0, 1}


def test_range_field_term_containment(ctx):
    assert matching(ctx, {"term": {"age_range": {"value": 45}}}) == {2}
    assert matching(ctx, {"term": {"age_range": {"value": 15}}}) == {0, 1}


def test_range_field_exists(ctx):
    assert matching(ctx, {"exists": {"field": "age_range"}}) == {0, 1, 2}


def test_range_field_rejects_scalar():
    svc = MapperService(mappings=MAPPINGS)
    with pytest.raises(MapperParsingException):
        svc.parse("x", {"age_range": 12})


def test_date_range_parses_dates():
    svc = MapperService(mappings=MAPPINGS)
    p = svc.parse("x", {"when": {"gte": "2024-01-01", "lt": "2024-02-01"}})
    lo = p.numeric_values["when.lo"][0]
    hi = p.numeric_values["when.hi"][0]
    assert lo < hi


# ---- wildcard field ----

def test_wildcard_field_wildcard_query(ctx):
    assert matching(ctx, {"wildcard": {"code": {"value": "alpha-*"}}}) == {0, 2}
    assert matching(ctx, {"wildcard": {"code": {"value": "*-456"}}}) == {1}


def test_wildcard_field_term_query(ctx):
    assert matching(ctx, {"term": {"code": "beta-456"}}) == {1}


# ---- constant_keyword ----

def test_constant_keyword_term_matches_all(ctx):
    assert matching(ctx, {"term": {"env": "prod"}}) == {0, 1, 2}
    assert matching(ctx, {"term": {"env": "staging"}}) == set()


def test_constant_keyword_exists_matches_all(ctx):
    assert matching(ctx, {"exists": {"field": "env"}}) == {0, 1, 2}


def test_constant_keyword_rejects_other_value():
    svc = MapperService(mappings=MAPPINGS)
    with pytest.raises(MapperParsingException):
        svc.parse("x", {"env": "staging"})


def test_constant_keyword_pins_first_value():
    svc = MapperService(mappings={"properties": {
        "dc": {"type": "constant_keyword"}}})
    svc.parse("a", {"dc": "us-east"})
    with pytest.raises(MapperParsingException):
        svc.parse("b", {"dc": "eu-west"})


# ---- rank_feature(s) ----

def test_rank_feature_saturation(ctx):
    scores, mask = run(ctx, {"rank_feature": {"field": "pagerank",
                                              "saturation": {"pivot": 5.0}}})
    assert set(np.nonzero(mask)[0]) == {0, 1, 2}
    assert scores[0] == pytest.approx(10 / 15)
    assert scores[1] == pytest.approx(2 / 7)
    assert scores[0] > scores[2] > scores[1]


def test_rank_feature_log(ctx):
    scores, _ = run(ctx, {"rank_feature": {"field": "pagerank",
                                           "log": {"scaling_factor": 1.0}}})
    assert scores[0] == pytest.approx(np.log(11.0), rel=1e-5)


def test_rank_feature_sigmoid(ctx):
    scores, _ = run(ctx, {"rank_feature": {
        "field": "pagerank", "sigmoid": {"pivot": 5.0, "exponent": 1.0}}})
    assert scores[2] == pytest.approx(0.5)


def test_rank_feature_negative_impact(ctx):
    scores, mask = run(ctx, {"rank_feature": {
        "field": "inverse_rank", "saturation": {"pivot": 0.5}}})
    # lower feature value => higher score
    assert mask[0] and mask[1] and not mask[2]
    assert scores[0] > scores[1]


def test_rank_features_query(ctx):
    scores, mask = run(ctx, {"rank_feature": {
        "field": "topics.sports", "saturation": {"pivot": 1.0}}})
    assert set(np.nonzero(mask)[0]) == {0, 2}
    assert scores[0] > scores[2]


def test_rank_feature_rejects_nonpositive():
    svc = MapperService(mappings=MAPPINGS)
    with pytest.raises(MapperParsingException):
        svc.parse("x", {"pagerank": -1.0})
    with pytest.raises(MapperParsingException):
        svc.parse("x", {"topics": {"a": 0.0}})


# ---- search_as_you_type ----

def test_sayt_match_on_root(ctx):
    assert matching(ctx, {"match": {"title": "quick"}}) == {0, 1}


def test_sayt_2gram_shingles(ctx):
    assert matching(ctx, {"match": {"title._2gram": "quick brown"}}) == {0}
    assert matching(ctx, {"match": {"title._2gram": "brown fox"}}) == {0}
    assert matching(ctx, {"match": {"title._2gram": "quick"}}) == set()


def test_sayt_3gram_shingles(ctx):
    assert matching(ctx, {"match": {"title._3gram": "quick brown fox"}}) == {0}


def test_sayt_index_prefix(ctx):
    assert matching(ctx, {"term": {"title._index_prefix": "bra"}}) == {1}
    assert matching(ctx, {"term": {"title._index_prefix": "qu"}}) == {0, 1}


def test_sayt_bool_prefix(ctx):
    # the search-as-you-type headline use (ref: match_bool_prefix docs)
    assert matching(ctx, {"match_bool_prefix": {"title": "quick br"}}) == {0, 1}


def test_sayt_subfields_hidden_from_mapping():
    svc = MapperService(mappings=MAPPINGS)
    props = svc.mapper.to_mapping()["properties"]
    assert "title" in props
    assert "_2gram" not in str(props["title"])


# ---- token_count / murmur3 ----

def test_token_count(ctx):
    assert matching(ctx, {"range": {"title_len": {"gte": 3}}}) == {0}
    assert matching(ctx, {"term": {"title_len": 2}}) == {1}


def test_murmur3_same_value_same_hash(ctx):
    seg = ctx.segment
    nv = seg.numerics["h"]
    assert nv.values[0] == nv.values[2]
    assert nv.values[0] != nv.values[1]


# ---- flattened ----

@pytest.fixture(scope="module")
def flat_ctx():
    svc = MapperService(mappings={"properties": {
        "labels": {"type": "flattened"}}})
    w = SegmentWriter()
    docs = [
        {"labels": {"priority": "urgent", "release": ["v1.2", "v1.3"],
                    "owner": {"team": "infra"}}},
        {"labels": {"priority": "low", "owner": {"team": "web"}}},
    ]
    for i, d in enumerate(docs):
        w.add(svc.parse(str(i), d))
    seg = w.build("s0")
    return SegmentContext(seg, DeviceSegment(seg), svc, ShardStats([seg]))


def test_flattened_keyed_term(flat_ctx):
    assert matching(flat_ctx, {"term": {"labels.priority": "urgent"}}) == {0}
    assert matching(flat_ctx, {"term": {"labels.owner.team": "web"}}) == {1}
    assert matching(flat_ctx, {"term": {"labels.release": "v1.3"}}) == {0}


def test_flattened_root_matches_any_value(flat_ctx):
    assert matching(flat_ctx, {"term": {"labels": "urgent"}}) == {0}
    assert matching(flat_ctx, {"term": {"labels": "infra"}}) == {0}


def test_flattened_rejects_scalar():
    svc = MapperService(mappings={"properties": {
        "labels": {"type": "flattened"}}})
    with pytest.raises(MapperParsingException):
        svc.parse("x", {"labels": "not-an-object"})


# ---------------------------------------------------------------------------
# annotated_text (ref: plugins/mapper-annotated-text/.../
# AnnotatedTextFieldMapper.java — markdown-like [anchor](value&value)
# markup; annotation values index as same-position tokens over the
# anchor so entity searches hit where the anchor text matched)
# ---------------------------------------------------------------------------

def test_annotated_text_parse():
    from elasticsearch_tpu.index.mapper import parse_annotated_text
    plain, anns = parse_annotated_text(
        "New mayor is [John Smith](John%20Smith&Person) of "
        "[Boston](Location)")
    assert plain == "New mayor is John Smith of Boston"
    assert anns == [(13, 23, ["John Smith", "Person"]),
                    (27, 33, ["Location"])]
    # key=value annotations are rejected (ref: AnnotatedText.parse)
    from elasticsearch_tpu.common.errors import MapperParsingException
    import pytest as _pytest
    with _pytest.raises(MapperParsingException):
        parse_annotated_text("[x](type=person)")


def test_annotated_text_search(tmp_path):
    from elasticsearch_tpu.node import Node
    node = Node(data_path=str(tmp_path / "ann"))
    try:
        c = node.rest_controller
        st, r = c.dispatch("PUT", "/news", None, {
            "mappings": {"properties": {
                "body": {"type": "annotated_text"}}}})
        assert st == 200, r
        c.dispatch("PUT", "/news/_doc/1", None, {
            "body": "New mayor is [John Smith](Person&q42) of the city"})
        c.dispatch("PUT", "/news/_doc/2", None, {
            "body": "John Smith went home"})
        c.dispatch("POST", "/news/_refresh", None, None)
        # plain text matches both
        st, r = c.dispatch("POST", "/news/_search", None,
                           {"query": {"match": {"body": "smith"}}})
        assert r["hits"]["total"]["value"] == 2
        # annotation values are single VERBATIM tokens (the injector
        # bypasses the analyzer chain, ref: AnnotationsInjector) — term
        # queries hit them exactly, only on the annotated doc
        st, r = c.dispatch("POST", "/news/_search", None,
                           {"query": {"term": {"body": "Person"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
        st, r = c.dispatch("POST", "/news/_search", None,
                           {"query": {"term": {"body": "person"}}})
        assert r["hits"]["total"]["value"] == 0     # case-exact
        # positions survive markup stripping: phrase across the anchor
        st, r = c.dispatch("POST", "/news/_search", None, {
            "query": {"match_phrase": {"body": "mayor is john smith"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
        # annotations are postings-searchable but phrase-invisible
        # (the positional stream keeps the anchor text token; the
        # reference's synonym-position tokens would also phrase-match —
        # disclosed divergence at the stream layer)
        st, r = c.dispatch("POST", "/news/_search", None, {
            "query": {"bool": {"must": [
                {"term": {"body": "q42"}},
                {"match_phrase": {"body": "john smith of the city"}}]}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    finally:
        node.close()
