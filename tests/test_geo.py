"""Geo layer tests: point parsing, distance/bbox/polygon/shape queries,
geo aggs, geo sort (model: the reference's GeoDistanceQueryBuilderTests,
GeoBoundingBoxQueryBuilderTests, GeoHashGridAggregatorTests)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.common.geo import (
    bbox_contains,
    geohash_decode,
    geohash_encode,
    haversine_meters,
    parse_distance,
    parse_geo_point,
    points_in_polygon,
    shape_bbox,
)
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops.device import DeviceSegment
from elasticsearch_tpu.search.aggregations import compute_aggs
from elasticsearch_tpu.search.context import SegmentContext, ShardStats
from elasticsearch_tpu.search.queries import parse_query

MAPPINGS = {
    "properties": {
        "name": {"type": "keyword"},
        "location": {"type": "geo_point"},
        "area": {"type": "geo_shape"},
    }
}

# real city coordinates make the distance assertions meaningful
CITIES = [
    {"name": "london", "location": {"lat": 51.5074, "lon": -0.1278}},
    {"name": "paris", "location": "48.8566,2.3522"},
    {"name": "berlin", "location": [13.4050, 52.5200]},        # [lon, lat]
    {"name": "sf", "location": {"lat": 37.7749, "lon": -122.4194}},
    {"name": "noloc"},
    {"name": "poly", "area": {"type": "polygon", "coordinates": [
        [[0.0, 0.0], [10.0, 0.0], [10.0, 10.0], [0.0, 10.0], [0.0, 0.0]]]}},
]


@pytest.fixture(scope="module")
def ctx():
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i, d in enumerate(CITIES):
        w.add(svc.parse(str(i), d))
    seg = w.build("s0")
    return SegmentContext(seg, DeviceSegment(seg), svc, ShardStats([seg]))


def matching(ctx, query_dict):
    q = parse_query(query_dict)
    _, mask = q.execute(ctx)
    return set(np.nonzero(np.asarray(mask)[: ctx.segment.n_docs])[0].tolist())


# ---- parsing ----

def test_parse_geo_point_formats():
    assert parse_geo_point({"lat": 1.0, "lon": 2.0}) == (1.0, 2.0)
    assert parse_geo_point("1.0,2.0") == (1.0, 2.0)
    assert parse_geo_point([2.0, 1.0]) == (1.0, 2.0)  # [lon, lat]
    assert parse_geo_point("POINT (2.0 1.0)") == (1.0, 2.0)
    lat, lon = parse_geo_point(geohash_encode(1.0, 2.0, 9))
    assert abs(lat - 1.0) < 1e-3 and abs(lon - 2.0) < 1e-3


def test_parse_geo_point_errors():
    with pytest.raises(IllegalArgumentException):
        parse_geo_point({"lat": 91.0, "lon": 0.0})
    with pytest.raises(IllegalArgumentException):
        parse_geo_point({"lat": 0.0, "lon": 181.0})
    with pytest.raises(ParsingException):
        parse_geo_point({"lat": 1.0})


def test_parse_distance_units():
    assert parse_distance("1km") == 1000.0
    assert parse_distance("1mi") == pytest.approx(1609.344)
    assert parse_distance(500) == 500.0
    assert parse_distance("2.5m") == 2.5
    with pytest.raises(ParsingException):
        parse_distance("10lightyears")


def test_geohash_roundtrip():
    for lat, lon in [(51.5, -0.12), (-33.86, 151.2), (0.0, 0.0)]:
        h = geohash_encode(lat, lon, 12)
        dlat, dlon = geohash_decode(h)
        assert abs(dlat - lat) < 1e-5
        assert abs(dlon - lon) < 1e-5


def test_haversine_known_distance():
    # London -> Paris ≈ 344 km
    d = haversine_meters(51.5074, -0.1278, 48.8566, 2.3522)
    assert 330_000 < d < 360_000


def test_points_in_polygon():
    lats = np.array([5.0, 15.0, -1.0, 9.9])
    lons = np.array([5.0, 5.0, 5.0, 9.9])
    poly_lats = [0.0, 0.0, 10.0, 10.0]
    poly_lons = [0.0, 10.0, 10.0, 0.0]
    inside = points_in_polygon(lats, lons, poly_lats, poly_lons)
    assert inside.tolist() == [True, False, False, True]


def test_shape_bbox():
    assert shape_bbox({"type": "point", "coordinates": [2.0, 1.0]}) == \
        (1.0, 2.0, 1.0, 2.0)
    assert shape_bbox({"type": "envelope",
                       "coordinates": [[-1.0, 5.0], [3.0, -2.0]]}) == \
        (-2.0, -1.0, 5.0, 3.0)
    b = shape_bbox({"type": "polygon", "coordinates": [
        [[0.0, 0.0], [10.0, 0.0], [10.0, 10.0], [0.0, 0.0]]]})
    assert b == (0.0, 0.0, 10.0, 10.0)


# ---- queries ----

def test_geo_distance_query(ctx):
    # 500 km around London: London + Paris
    hits = matching(ctx, {"geo_distance": {
        "distance": "500km", "location": {"lat": 51.5074, "lon": -0.1278}}})
    assert hits == {0, 1}


def test_geo_distance_query_excludes_missing(ctx):
    hits = matching(ctx, {"geo_distance": {
        "distance": "25000km", "location": {"lat": 0, "lon": 0}}})
    assert 4 not in hits          # no location field
    assert {0, 1, 2, 3} <= hits


def test_geo_bounding_box_query(ctx):
    # box around continental europe
    hits = matching(ctx, {"geo_bounding_box": {"location": {
        "top_left": {"lat": 55.0, "lon": 0.0},
        "bottom_right": {"lat": 45.0, "lon": 15.0}}}})
    assert hits == {1, 2}


def test_geo_bounding_box_dateline(ctx):
    # box crossing the antimeridian includes SF (lon -122)
    hits = matching(ctx, {"geo_bounding_box": {"location": {
        "top": 60.0, "left": 150.0, "bottom": 30.0, "right": -110.0}}})
    assert hits == {3}


def test_geo_polygon_query(ctx):
    # triangle around Paris
    hits = matching(ctx, {"geo_polygon": {"location": {"points": [
        {"lat": 50.0, "lon": 0.0}, {"lat": 50.0, "lon": 5.0},
        {"lat": 47.0, "lon": 2.0}]}}})
    assert hits == {1}


def test_geo_shape_query_intersects(ctx):
    hits = matching(ctx, {"geo_shape": {"area": {
        "shape": {"type": "envelope",
                  "coordinates": [[5.0, 8.0], [15.0, 2.0]]},
        "relation": "intersects"}}})
    assert hits == {5}


def test_geo_shape_query_disjoint(ctx):
    hits = matching(ctx, {"geo_shape": {"area": {
        "shape": {"type": "envelope",
                  "coordinates": [[20.0, 30.0], [25.0, 25.0]]},
        "relation": "disjoint"}}})
    assert hits == {5}


def test_geo_shape_query_within(ctx):
    hits = matching(ctx, {"geo_shape": {"area": {
        "shape": {"type": "envelope",
                  "coordinates": [[-5.0, 15.0], [15.0, -5.0]]},
        "relation": "within"}}})
    assert hits == {5}


# ---- aggs ----

def _agg_ctx(ctx):
    seg = ctx.segment
    mask = np.ones(seg.n_docs, bool)
    return [(seg, mask, ctx.mapper)]


def test_geo_distance_agg(ctx):
    out = compute_aggs({"rings": {"geo_distance": {
        "field": "location", "origin": "51.5074,-0.1278", "unit": "km",
        "ranges": [{"to": 100}, {"from": 100, "to": 1000},
                   {"from": 1000}]}}}, _agg_ctx(ctx), ctx.mapper)
    b = out["rings"]["buckets"]
    assert b[0]["doc_count"] == 1          # london
    assert b[1]["doc_count"] == 2          # paris, berlin
    assert b[2]["doc_count"] == 1          # sf


def test_geohash_grid_agg(ctx):
    out = compute_aggs({"cells": {"geohash_grid": {
        "field": "location", "precision": 3}}}, _agg_ctx(ctx), ctx.mapper)
    buckets = out["cells"]["buckets"]
    assert sum(b["doc_count"] for b in buckets) == 4
    keys = {b["key"] for b in buckets}
    from elasticsearch_tpu.common.geo import geohash_encode as ge
    assert ge(51.5074, -0.1278, 3) in keys


def test_geotile_grid_agg(ctx):
    out = compute_aggs({"cells": {"geotile_grid": {
        "field": "location", "precision": 4}}}, _agg_ctx(ctx), ctx.mapper)
    buckets = out["cells"]["buckets"]
    assert sum(b["doc_count"] for b in buckets) == 4
    assert all(b["key"].startswith("4/") for b in buckets)


def test_geo_bounds_agg(ctx):
    out = compute_aggs({"box": {"geo_bounds": {"field": "location"}}},
                       _agg_ctx(ctx), ctx.mapper)
    b = out["box"]["bounds"]
    assert b["top_left"]["lat"] == pytest.approx(52.52, abs=0.01)
    assert b["top_left"]["lon"] == pytest.approx(-122.4194, abs=0.01)


def test_geo_centroid_agg(ctx):
    out = compute_aggs({"c": {"geo_centroid": {"field": "location"}}},
                       _agg_ctx(ctx), ctx.mapper)
    assert out["c"]["count"] == 4
    assert -90 <= out["c"]["location"]["lat"] <= 90


# ---- sort ----

def test_geo_distance_sort():
    from elasticsearch_tpu.search.searcher import ShardSearcher

    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i, d in enumerate(CITIES[:4]):
        w.add(svc.parse(str(i), d))
    seg = w.build("s0")
    searcher = ShardSearcher([seg], svc)
    q = parse_query({"match_all": {}})
    result = searcher.query_phase(
        q, size=4,
        sort=[{"_geo_distance": {"location": {"lat": 51.5, "lon": -0.12},
                                 "order": "asc", "unit": "km"}}])
    docs = result.docs
    ids = [d.docid for d in docs]
    assert ids == [0, 1, 2, 3]   # london, paris, berlin, sf
    # sort values are distances in km, ascending
    dists = [d.sort_values[0] for d in docs]
    assert dists[0] < 5
    assert 300 < dists[1] < 400
    assert dists == sorted(dists)


def test_geo_distance_sort_search_after():
    """search_after pagination with a _geo_distance sort (regression: the
    cursor column used to resolve to a missing numeric field → zero hits)."""
    from elasticsearch_tpu.search.searcher import ShardSearcher

    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i, d in enumerate(CITIES[:4]):
        w.add(svc.parse(str(i), d))
    seg = w.build("s0")
    searcher = ShardSearcher([seg], svc)
    sort = [{"_geo_distance": {"location": "51.5,-0.12", "order": "asc",
                               "unit": "km"}}]
    q = parse_query({"match_all": {}})
    page1 = searcher.query_phase(q, size=2, sort=sort)
    assert [d.docid for d in page1.docs] == [0, 1]
    after = list(page1.docs[-1].sort_values)
    page2 = searcher.query_phase(q, size=2, sort=sort, search_after=after)
    assert [d.docid for d in page2.docs] == [2, 3]


def test_geo_distance_sort_missing_field_is_parse_error():
    from elasticsearch_tpu.search.searcher import _parse_sort
    with pytest.raises(ParsingException):
        _parse_sort([{"_geo_distance": {"order": "asc"}}])


def test_geo_distance_agg_unknown_unit(ctx):
    with pytest.raises(IllegalArgumentException):
        compute_aggs({"rings": {"geo_distance": {
            "field": "location", "origin": "0,0", "unit": "lightyears",
            "ranges": [{"to": 1}]}}}, _agg_ctx(ctx), ctx.mapper)


def test_geo_point_multi_value():
    svc = MapperService(mappings=MAPPINGS)
    parsed = svc.parse("0", {"location": [[2.0, 1.0], [4.0, 3.0]]})
    assert parsed.numeric_values["location.lat"] == [1.0, 3.0]
    assert parsed.numeric_values["location.lon"] == [2.0, 4.0]
