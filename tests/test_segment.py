"""Segment format tests (model: Lucene index round-trip tests; validates the
padded-block postings invariants the kernels rely on)."""

import numpy as np
import pytest

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import (
    BLOCK_SIZE,
    Segment,
    SegmentWriter,
    merge_segments,
)

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "n": {"type": "long"},
        "vec": {"type": "dense_vector", "dims": 3},
    }
}


def build_segment(docs, name="s0"):
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i, src in enumerate(docs):
        w.add(svc.parse(str(i), src))
    return w.build(name)


def test_postings_roundtrip():
    seg = build_segment([
        {"body": "the quick brown fox", "tag": "a", "n": 1},
        {"body": "the lazy dog", "tag": "b", "n": 2},
        {"body": "quick quick dog", "tag": "a"},
    ])
    pf = seg.postings["body"]
    docids, tfs = pf.postings("quick")
    assert docids.tolist() == [0, 2]
    assert tfs.tolist() == [1.0, 2.0]
    docids, tfs = pf.postings("the")
    assert docids.tolist() == [0, 1]
    assert pf.term_id("missing") == -1
    assert pf.postings("missing")[0].size == 0
    # stats
    assert pf.doc_count == 3
    assert pf.sum_total_term_freq == 4 + 3 + 3
    assert pf.field_lengths.tolist() == [4.0, 3.0, 3.0]


def test_block_padding_invariants():
    # a term with > BLOCK_SIZE postings spans multiple blocks; padding has tf=0
    docs = [{"body": "common"} for _ in range(BLOCK_SIZE + 10)]
    docs.append({"body": "rare"})
    seg = build_segment(docs)
    pf = seg.postings["body"]
    start, count = pf.term_blocks("common")
    assert count == 2
    blk = pf.block_tfs[start : start + count]
    assert (blk.reshape(-1) > 0).sum() == BLOCK_SIZE + 10
    # rare term's block is its own — never shares with 'common'
    rstart, rcount = pf.term_blocks("rare")
    assert rcount == 1
    assert rstart >= start + count
    docids, _ = pf.postings("rare")
    assert docids.tolist() == [BLOCK_SIZE + 10]


def test_block_max_metadata_is_valid_bound():
    rng = np.random.default_rng(7)
    docs = [{"body": " ".join(rng.choice(["a", "b", "c", "d"], size=rng.integers(1, 30)))}
            for _ in range(300)]
    seg = build_segment(docs)
    pf = seg.postings["body"]
    k1, b = 1.2, 0.75
    avg = pf.avg_field_length
    for blk in range(pf.num_blocks):
        tfs = pf.block_tfs[blk]
        dids = pf.block_docids[blk]
        mask = tfs > 0
        if not mask.any():
            continue
        lens = pf.field_lengths[dids[mask]]
        actual = tfs[mask] / (tfs[mask] + k1 * (1 - b + b * lens / avg))
        bound_tf = pf.block_max_tf[blk]
        bound = bound_tf / (bound_tf + k1 * (1 - b + b * pf.block_min_len[blk] / avg))
        assert actual.max() <= bound + 1e-6


def test_doc_values_and_vectors():
    seg = build_segment([
        {"n": 5, "vec": [1.0, 0.0, 0.0], "tag": ["x", "y"]},
        {"body": "no numeric"},
        {"n": 7},
    ])
    nv = seg.numerics["n"]
    assert nv.values[0] == 5.0 and nv.values[2] == 7.0
    assert nv.missing.tolist() == [False, True, False]
    assert nv.get(0) == [5.0]
    kv = seg.keywords["tag"]
    assert kv.get(0) == ["x", "y"]
    assert kv.get(1) == []
    vv = seg.vectors["vec"]
    assert vv.has_value.tolist() == [True, False, False]
    assert np.allclose(vv.vectors[0], [1, 0, 0])


def test_stored_fields_and_ids():
    seg = build_segment([{"body": "hello"}, {"body": "world", "n": 2}])
    import json
    assert json.loads(seg.stored.source(1)) == {"body": "world", "n": 2}
    assert seg.docid_for("1") == 1
    assert seg.docid_for("404") == -1


def test_save_load_roundtrip(tmp_path):
    seg = build_segment([
        {"body": "the quick brown fox", "tag": "a", "n": 1, "vec": [1.0, 2.0, 3.0]},
        {"body": "lazy dog", "tag": "b", "n": 2},
    ])
    seg.delete(1)
    seg.save(str(tmp_path / "seg"))
    loaded = Segment.load(str(tmp_path / "seg"))
    assert loaded.n_docs == 2
    assert loaded.live.tolist() == [True, False]
    pf0, pf1 = seg.postings["body"], loaded.postings["body"]
    assert pf0.terms == pf1.terms
    np.testing.assert_array_equal(pf0.block_docids, pf1.block_docids)
    np.testing.assert_array_equal(pf0.block_tfs, pf1.block_tfs)
    assert loaded.numerics["n"].values.tolist() == [1.0, 2.0]
    assert np.allclose(loaded.vectors["vec"].vectors[0], [1, 2, 3])
    assert loaded.stored.ids == ["0", "1"]
    assert loaded.keywords["tag"].get(0) == ["a"]


def test_merge_drops_deletes_and_remaps():
    seg1 = build_segment([
        {"body": "alpha beta", "tag": "a", "n": 1},
        {"body": "beta gamma", "tag": "b", "n": 2},
    ], "s1")
    seg2 = build_segment([
        {"body": "gamma delta", "tag": "a", "n": 3, "vec": [1.0, 0.0, 0.0]},
    ], "s2")
    seg1.delete(0)
    merged = merge_segments("m", [seg1, seg2])
    assert merged.n_docs == 2
    pf = merged.postings["body"]
    assert pf.postings("alpha")[0].size == 0 or "alpha" not in pf.terms
    docids, _ = pf.postings("gamma")
    assert docids.tolist() == [0, 1]  # old seg1/doc1 -> 0, seg2/doc0 -> 1
    assert merged.numerics["n"].values.tolist() == [2.0, 3.0]
    assert merged.keywords["tag"].get(0) == ["b"]
    assert merged.vectors["vec"].has_value.tolist() == [False, True]
    assert merged.stored.ids == ["1", "0"]
    # stats rebuilt
    assert pf.doc_count == 2


def test_merge_preserves_field_lengths():
    seg1 = build_segment([{"body": "one two three"}], "s1")
    seg2 = build_segment([{"body": "four"}], "s2")
    merged = merge_segments("m", [seg1, seg2])
    assert merged.postings["body"].field_lengths.tolist() == [3.0, 1.0]
    assert merged.postings["body"].avg_field_length == 2.0
