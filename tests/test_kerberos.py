"""Kerberos realm + RFC 3961/3962 crypto tests.

The n-fold and string-to-key cases are the RFCs' published test vectors
— external ground truth for the hand-written crypto (ref parity:
KerberosRealmTests / KerberosTicketValidatorTests validate against a
real KDC fixture; here the 'KDC' is build_ap_req over the same RFC
primitives, and the primitives themselves are pinned to the RFCs)."""

import base64
import datetime
import json

import pytest

from elasticsearch_tpu.common import krb5


# ------------------------------------------------------ RFC 3961 A.1

@pytest.mark.parametrize("data,nbytes,expect", [
    (b"012345", 8, "be072631276b1955"),
    (b"password", 7, "78a07b6caf85fa"),
    (b"Rough Consensus, and Running Code", 8, "bb6ed30870b7f0e0"),
    (b"kerberos", 8, "6b65726265726f73"),
    (b"kerberos", 16, "6b65726265726f737b9b5b2b93132b93"),
])
def test_nfold_rfc3961_vectors(data, nbytes, expect):
    assert krb5.nfold(data, nbytes).hex() == expect


# ------------------------------------------------------ RFC 3962 B

@pytest.mark.parametrize("iters,password,salt,k128,k256", [
    (1, "password", "ATHENA.MIT.EDUraeburn",
     "42263c6e89f4fc28b8df68ee09799f15",
     "fe697b52bc0d3ce14432ba036a92e65bbb52280990a2fa27883998d72af30161"),
    (2, "password", "ATHENA.MIT.EDUraeburn",
     "c651bf29e2300ac27fa469d693bdda13",
     "a2e16d16b36069c135d5e9d2e25f896102685618b95914b467c67622225824ff"),
    (1200, "password", "ATHENA.MIT.EDUraeburn",
     "4c01cd46d632d01e6dbe230a01ed642a",
     "55a6ac740ad17b4846941051e1e8b0a7548d93b0ab30a8bc3ff16280382b8c2a"),
])
def test_string_to_key_rfc3962_vectors(iters, password, salt, k128, k256):
    assert krb5.string_to_key(password, salt, iters, 16).hex() == k128
    assert krb5.string_to_key(password, salt, iters, 32).hex() == k256


# ------------------------------------------------------ encrypt/decrypt

@pytest.mark.parametrize("keylen", [16, 32])
@pytest.mark.parametrize("size", [1, 15, 16, 17, 31, 32, 100, 1000])
def test_krb_encrypt_roundtrip(keylen, size):
    key = bytes(range(keylen))
    plain = bytes(i % 251 for i in range(size))
    blob = krb5.krb_encrypt(key, 2, plain)
    assert krb5.krb_decrypt(key, 2, blob) == plain
    # wrong usage / tamper / wrong key all fail the MAC
    with pytest.raises(krb5.KrbError):
        krb5.krb_decrypt(key, 3, blob)
    with pytest.raises(krb5.KrbError):
        krb5.krb_decrypt(bytes(keylen), 2, blob)
    bad = bytearray(blob)
    bad[len(bad) // 2] ^= 1
    with pytest.raises(krb5.KrbError):
        krb5.krb_decrypt(key, 2, bytes(bad))


# ------------------------------------------------------ SPNEGO/AP-REQ

SVC = "HTTP/es.example.com"
KEY = krb5.string_to_key("s3cr3t", "EXAMPLE.COM" + SVC)


def make_token(cname="alice", crealm="EXAMPLE.COM", key=KEY,
               endtime=None, etype=krb5.ETYPE_AES256):
    ap = krb5.build_ap_req(SVC, "EXAMPLE.COM", key, cname, crealm,
                           endtime=endtime, etype=etype)
    return krb5.spnego_wrap(ap)


def test_validate_spnego_roundtrip():
    res = krb5.validate_spnego(make_token(), {SVC: KEY})
    assert res == {"principal": "alice@EXAMPLE.COM", "name": "alice",
                   "realm": "EXAMPLE.COM"}


def test_validate_spnego_aes128():
    key = krb5.string_to_key("pw", "x", keylen=16)
    tok = make_token(key=key, etype=krb5.ETYPE_AES128)
    res = krb5.validate_spnego(tok, {SVC: key})
    assert res["name"] == "alice"


def test_validate_wrong_service_key():
    with pytest.raises(krb5.KrbError, match="integrity"):
        krb5.validate_spnego(make_token(), {SVC: bytes(32)})


def test_validate_unknown_service():
    with pytest.raises(krb5.KrbError, match="keytab"):
        krb5.validate_spnego(make_token(), {"HTTP/other": KEY})


def test_validate_expired_ticket():
    past = datetime.datetime.now(datetime.timezone.utc) \
        - datetime.timedelta(hours=1)
    with pytest.raises(krb5.KrbError, match="expired"):
        krb5.validate_spnego(make_token(endtime=past), {SVC: KEY})


@pytest.mark.parametrize("mutate", [
    lambda t: b"",
    lambda t: b"\x00" * 40,
    lambda t: t[:20],
    lambda t: t[:60] + b"\xff" * 10 + t[70:],
    lambda t: bytes([t[0]]) + t[1:][::-1],
])
def test_malformed_tokens_raise_krberror_only(mutate):
    """Attacker-crafted garbage must surface as KrbError, never as a
    KeyError/IndexError 500 (advisor: unauthenticated parse path)."""
    tok = mutate(make_token())
    with pytest.raises(krb5.KrbError):
        krb5.validate_spnego(tok, {SVC: KEY})


def test_deep_spnego_nesting_bounded():
    inner = krb5.spnego_wrap(b"\x00" * 8)
    for _ in range(10):
        mech_list = krb5.der_tlv(0x30, krb5.der_tlv(0x06, krb5.OID_KRB5))
        neg = krb5.der_tlv(0x30, krb5.der_ctx(0, mech_list)
                           + krb5.der_ctx(2, krb5.der_tlv(0x04, inner)))
        inner = krb5.der_tlv(
            0x60, krb5.der_tlv(0x06, krb5.OID_SPNEGO)
            + krb5.der_ctx(0, neg))
    with pytest.raises(krb5.KrbError):
        krb5.validate_spnego(inner, {SVC: KEY})


# ------------------------------------------------------ realm + REST

def test_kerberos_realm_end_to_end(tmp_path):
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node
    keytab = tmp_path / "keytab.json"
    keytab.write_text(json.dumps({SVC: KEY.hex()}))
    node = Node(settings=Settings.from_dict({
        "xpack": {"security": {"enabled": True, "authc": {"kerberos": {
            "keytab_path": str(keytab)}}}},
    }), data_path=str(tmp_path / "node"))
    try:
        node.security_service.put_role_mapping("kerb", {
            "roles": ["superuser"],
            "rules": {"field": {"username": "alice@EXAMPLE.COM"}},
            "enabled": True})
        tok = base64.b64encode(make_token()).decode()
        st, me = node.rest_controller.dispatch(
            "GET", "/_security/_authenticate", None, None,
            {"Authorization": f"Negotiate {tok}"})
        assert st == 200 and me["username"] == "alice@EXAMPLE.COM"
        assert "superuser" in me["roles"]
        # 401s advertise the Negotiate challenge
        st, body = node.rest_controller.dispatch(
            "GET", "/_cluster/health", None, None, {})
        assert st == 401
        assert "Negotiate" in body["_headers"]["WWW-Authenticate"]
        # garbage token → clean 401, not a 500
        st, _ = node.rest_controller.dispatch(
            "GET", "/_security/_authenticate", None, None,
            {"Authorization": "Negotiate AAAA"})
        assert st == 401
    finally:
        node.close()
