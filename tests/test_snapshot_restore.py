"""Cluster-durable snapshot/restore under the deterministic harness:
a master-coordinated distributed snapshot (per-shard child uploads to
the shared blob repository) taken under live write/search load without
blocking writes, cancel-from-any-side releasing every resource
(leases, breaker bytes, tasks, partial blobs), segment-granular
incremental uploads, restore riding the staged recovery protocol —
including into a FRESH cluster after full-cluster loss with wiped data
dirs — and SLM policies executing against the cluster path on the
scheduler clock.

Every chaos path replays byte-identically from its queue seed."""

import shutil

import pytest

from test_cluster_node import SimDataCluster, _index_some_docs

from elasticsearch_tpu.utils.breaker import CircuitBreaker


@pytest.fixture()
def cluster(tmp_path):
    return SimDataCluster(3, tmp_path, seed=31)


# ------------------------------------------------------------------ helpers

def _put_repo(cluster, master, location):
    resp = cluster.call(master.put_repository, "backup",
                        {"type": "fs", "settings": {"location": location}})
    assert resp["acknowledged"] is True, resp


def _sorted_hits(cluster, coordinator, index, size=400):
    resp = cluster.call(coordinator.search, index,
                        {"query": {"match_all": {}}, "size": size,
                         "sort": [{"n": "asc"}]})
    assert resp["_shards"]["failed"] == 0, resp
    return [(h["_id"], h["_source"]) for h in resp["hits"]["hits"]]


def _assert_no_snapshot_leaks(cluster):
    """The cluster-wide postcondition every snapshot exit (success,
    failure, cancel) must leave behind: no history-pinning leases, no
    in-flight handles, no breaker bytes, no registered tasks, nothing
    in the master's in-progress table."""
    for cn in cluster.cluster_nodes.values():
        for key, shard in cn.data_node.shards.items():
            if shard.tracker is None:
                continue
            leases = shard.tracker.get_retention_leases()
            leaked = [lid for lid in leases if lid.startswith("snapshot/")]
            assert not leaked, f"{key}: leaked snapshot leases {leaked}"
        assert cn.data_node.shard_snapshots == {}, \
            cn.data_node.shard_snapshots
        assert cn.breaker_service.get_breaker(
            CircuitBreaker.REQUEST).used == 0
        assert not cn.task_manager.list_tasks(actions="*snapshot*")
        assert cn.snapshots.in_progress == {}


def _repo_shard_meta(master, snapshot, index="logs"):
    repo = master.repositories.get_repository("backup")
    return repo.get_snapshot(snapshot)["indices"][index]["shards"]


def _staggered_bulks(cluster, coordinator, acked, rounds=10, batch=4,
                     gap=0.3, index="logs", start_n=1000):
    """Spread bulk writes across the snapshot window, recording acked
    ids (the load the snapshot must stay seqno-consistent under)."""
    counter = {"n": start_n}

    def one_round():
        items = []
        for _ in range(batch):
            i = counter["n"]
            counter["n"] += 1
            items.append({"op": "index", "id": f"live-{i}",
                          "source": {"body": f"live doc {i}", "n": i}})

        def on_done(resp, err=None, _items=items):
            if err is not None:
                return
            for item, d in zip(resp["items"], _items):
                if item and "error" not in item:
                    acked.append(d["id"])

        coordinator.bulk(index, items, on_done=on_done)

    for r in range(rounds):
        cluster.queue.schedule(r * gap, one_round,
                               f"staggered-bulk[{r}]")


# --------------------------------------------- snapshot under live load

def test_snapshot_under_concurrent_load_is_seqno_consistent(cluster):
    """A snapshot taken while bulks and searches are in flight
    completes without blocking writes; the restored copy contains
    every doc acked before the snapshot started and nothing torn."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=1)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=30)
    baseline = _sorted_hits(cluster, master, "logs")
    assert len(baseline) == 30

    acked = []
    _staggered_bulks(cluster, master, acked, rounds=12, gap=0.25)
    snap = cluster.call(master.create_snapshot, "backup", "live-snap",
                        {"indices": "logs"})
    assert snap["snapshot"]["state"] == "SUCCESS", snap
    assert snap["snapshot"]["shards"]["failed"] == 0

    # searches stayed up through the window, and the live writes kept
    # landing (the snapshot never blocked the write path)
    cluster.run_for(30)
    cluster.call(master.refresh)
    assert len(acked) > 0
    live = _sorted_hits(cluster, master, "logs")
    assert len(live) == 30 + len(acked)

    # restore next to the live index: every pre-snapshot doc is there,
    # and whatever slice of the live writes the consistency point
    # caught is a prefix-consistent subset of what was acked
    resp = cluster.call(master.restore_snapshot, "backup", "live-snap",
                        {"indices": "logs", "rename_pattern": "logs",
                         "rename_replacement": "logs_at_snap"})
    assert resp["accepted"] is True
    cluster.run_for(60)
    cluster.call(master.refresh)
    restored = _sorted_hits(cluster, master, "logs_at_snap")
    restored_ids = {i for i, _ in restored}
    assert {i for i, _ in baseline} <= restored_ids
    assert restored_ids <= {i for i, _ in live}
    assert restored[:30] == baseline
    _assert_no_snapshot_leaks(cluster)


# ----------------------------------------------------- cancel releases all

def test_delete_in_flight_snapshot_releases_everything(cluster):
    """DELETE of an in-flight snapshot cancels it cluster-wide: the
    uploading shards abort, partial blobs are dropped, every lease /
    breaker byte / task / in-progress entry is released, and the repo
    stays readable at its prior generation."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=1)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=60)
    # a completed first snapshot pins the repo generation to compare
    first = cluster.call(master.create_snapshot, "backup", "keeper",
                         {"indices": "logs"})
    assert first["snapshot"]["state"] == "SUCCESS"
    repo = master.repositories.get_repository("backup")
    gen_before = repo.load_repository_data()["gen"]

    # issue create (async) and delete back-to-back WITHOUT driving the
    # queue between them: the delete lands while shard uploads are
    # still stepping file-by-file
    create_box, delete_box = {}, {}
    master.create_snapshot(
        "backup", "doomed", {"indices": "logs"},
        wait_for_completion=False,
        on_done=lambda r, e: create_box.update(r=r, e=e))
    master.delete_snapshot(
        "backup", "doomed",
        on_done=lambda r, e: delete_box.update(r=r, e=e))
    cluster.run_for(90)

    assert delete_box.get("e") is None, delete_box
    assert create_box.get("e") is None and \
        create_box["r"].get("accepted") is True, create_box
    task_id = create_box["r"]["task"]
    # the cancelled create's failure is recorded as the task's result
    stored = master.task_results.get(task_id)
    assert stored is not None and "error" in stored, stored

    # repo readable at the PRIOR generation: the doomed snapshot never
    # became visible, the keeper still restores, integrity is clean
    data = repo.load_repository_data()
    assert data["gen"] == gen_before
    assert "doomed" not in data["snapshots"]
    assert "keeper" in data["snapshots"]
    assert repo.verify_integrity() == []
    _assert_no_snapshot_leaks(cluster)

    # and the cluster still takes a fresh snapshot afterwards
    again = cluster.call(master.create_snapshot, "backup", "after",
                         {"indices": "logs"})
    assert again["snapshot"]["state"] == "SUCCESS"
    _assert_no_snapshot_leaks(cluster)


# -------------------------------------------------------- incremental upload

def test_incremental_second_snapshot_uploads_zero_bytes(cluster):
    """Content-hash dedup at segment granularity: a second snapshot of
    an unchanged index uploads nothing; after new writes a third
    snapshot moves only the delta."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=40)

    s1 = cluster.call(master.create_snapshot, "backup", "snap1",
                      {"indices": "logs"})
    assert s1["snapshot"]["state"] == "SUCCESS"
    uploaded1 = sum(m["uploaded_bytes"]
                    for m in _repo_shard_meta(master, "snap1"))
    assert uploaded1 > 0

    s2 = cluster.call(master.create_snapshot, "backup", "snap2",
                      {"indices": "logs"})
    assert s2["snapshot"]["state"] == "SUCCESS"
    meta2 = _repo_shard_meta(master, "snap2")
    assert sum(m["uploaded_bytes"] for m in meta2) == 0, meta2
    assert sum(m["skipped_bytes"] for m in meta2) > 0

    # new writes: the third snapshot ships only what changed
    _index_some_docs(cluster, master, n=10)
    s3 = cluster.call(master.create_snapshot, "backup", "snap3",
                      {"indices": "logs"})
    assert s3["snapshot"]["state"] == "SUCCESS"
    meta3 = _repo_shard_meta(master, "snap3")
    uploaded3 = sum(m["uploaded_bytes"] for m in meta3)
    assert 0 < uploaded3 < uploaded1
    assert sum(m["skipped_bytes"] for m in meta3) > 0
    _assert_no_snapshot_leaks(cluster)


# ------------------------------------------------------- full-cluster loss

def test_full_cluster_loss_restore_into_fresh_cluster(tmp_path):
    """The disaster-recovery contract: every node stopped, every data
    dir wiped, a FRESH cluster (different seed, different node dirs)
    registers the same repository and restores — zero loss of writes
    acked before the snapshot, byte-identical search results vs the
    pre-loss baseline, recoveries riding the staged protocol with the
    repository as source."""
    repo_dir = str(tmp_path / "shared-backup")
    c1 = SimDataCluster(3, tmp_path / "c1", seed=31)
    m1 = c1.stabilise()
    _put_repo(c1, m1, repo_dir)
    c1.call(m1.create_index, "logs",
            number_of_shards=2, number_of_replicas=1)
    c1.run_for(30)
    _index_some_docs(c1, m1, n=40)
    baseline = _sorted_hits(c1, m1, "logs")
    assert len(baseline) == 40
    snap = c1.call(m1.create_snapshot, "backup", "doomsday",
                   {"indices": "logs"})
    assert snap["snapshot"]["state"] == "SUCCESS"
    # writes after the snapshot are lost by definition — they must not
    # resurrect or corrupt the restored copy
    _index_some_docs(c1, m1, n=45)

    for nid in list(c1.cluster_nodes):
        c1.stop_node(nid)
    for p in (tmp_path / "c1").iterdir():
        shutil.rmtree(p)

    c2 = SimDataCluster(3, tmp_path / "c2", seed=97)
    m2 = c2.stabilise()
    _put_repo(c2, m2, repo_dir)
    resp = c2.call(m2.restore_snapshot, "backup", "doomsday",
                   {"indices": "logs"})
    assert resp["accepted"] is True
    assert resp["snapshot"]["shards"]["failed"] == 0
    c2.run_for(90)

    c2.call(m2.refresh)
    restored = _sorted_hits(c2, m2, "logs")
    assert restored == baseline
    # the restore rode the staged recovery protocol from the repo
    snap_recs = [rec for cn in c2.cluster_nodes.values()
                 for rec in cn.data_node.recoveries.values()
                 if rec.recovery_type == "snapshot"]
    assert snap_recs and all(r.stage == "done" for r in snap_recs)
    assert all(r.source_node.startswith("_snapshot:") for r in snap_recs)
    _assert_no_snapshot_leaks(c2)

    # the restored index is a first-class citizen: writes + a fresh
    # snapshot work on top of it
    _index_some_docs(c2, m2, n=5)
    s2 = c2.call(m2.create_snapshot, "backup", "post-restore",
                 {"indices": "logs"})
    assert s2["snapshot"]["state"] == "SUCCESS"


# ------------------------------------------------------------ async create

def test_async_create_visible_in_tasks_with_stored_result(cluster):
    """``wait_for_completion=false``: the create is ACCEPTED with a
    task id, the parent task is visible in `_tasks` while shards
    upload, and the final snapshot info is served from the task-result
    store after completion."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=1)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=50)

    box = {}
    master.create_snapshot("backup", "bg-snap", {"indices": "logs"},
                           wait_for_completion=False,
                           on_done=lambda r, e: box.update(r=r, e=e))
    # drive in tiny slices: the parent task must be observable in
    # `_tasks` between the accept going out and the last shard
    # response coming back
    seen_live = False
    for _ in range(4000):
        cluster.run_for(0.005)
        if master.task_manager.list_tasks(actions="*snapshot/create*"):
            seen_live = True
        if ("r" in box or "e" in box) and seen_live:
            break
    assert box.get("e") is None and box["r"]["accepted"] is True, box
    task_id = box["r"]["task"]
    assert seen_live, "parent task never visible while snapshot in flight"

    cluster.run_for(60)
    result = cluster.call(master.get_task, task_id)
    assert result["completed"] is True, result
    assert result["response"]["snapshot"]["snapshot"] == "bg-snap"
    assert result["response"]["snapshot"]["state"] == "SUCCESS"
    _assert_no_snapshot_leaks(cluster)


# ------------------------------------------------------------------- SLM

def test_slm_policy_executes_and_schedules_on_cluster(cluster):
    """SLM on the cluster path: _execute creates a real distributed
    snapshot and stamps last_success; a ``schedule`` interval fires
    lazily off the scheduler clock; retention prunes to max_count."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=20)

    resp = cluster.call(master.slm_request, "put", "nightly",
                        {"repository": "backup",
                         "name": "<nightly-{now/d}>",
                         "config": {"indices": "logs"},
                         "schedule": "1h",
                         "retention": {"max_count": 2}})
    assert resp["acknowledged"] is True
    resp = cluster.call(master.slm_request, "execute", "nightly")
    first_snap = resp["snapshot_name"]
    assert first_snap.startswith("nightly-")
    cluster.run_for(30)

    pol = cluster.call(master.slm_request, "get", "nightly")
    assert pol["nightly"]["last_success"]["snapshot_name"] == first_snap

    def _policy_snapshots():
        repo = master.repositories.get_repository("backup")
        return sorted(s["snapshot"] for s in repo.list_snapshots()
                      if (s.get("metadata") or {}).get("policy")
                      == "nightly")

    assert _policy_snapshots() == [first_snap]
    # the schedule fires lazily when the policy surface is read past
    # the interval — no background timer perturbs the task queue
    cluster.run_for(3700)
    cluster.call(master.slm_request, "get")
    cluster.run_for(30)
    snaps = _policy_snapshots()
    assert len(snaps) == 2 and first_snap in snaps

    # two more fires: retention caps the fleet at max_count=2
    for _ in range(2):
        cluster.run_for(3700)
        cluster.call(master.slm_request, "get")
        cluster.run_for(30)
    assert len(_policy_snapshots()) == 2
    _assert_no_snapshot_leaks(cluster)


# ---------------------------------------------------------------- health

def test_repository_integrity_indicator_goes_red_on_damage(cluster):
    """The repository_integrity indicator: GREEN on a verified repo,
    typed RED with a corruption diagnosis once a referenced blob is
    destroyed."""
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=10)
    snap = cluster.call(master.create_snapshot, "backup", "snap1",
                        {"indices": "logs"})
    assert snap["snapshot"]["state"] == "SUCCESS"

    rep = cluster.call(master.health_report, "repository_integrity")
    ind = rep["indicators"]["repository_integrity"]
    assert ind["status"] == "green", ind

    # destroy a referenced segment blob behind the repo's back
    repo = master.repositories.get_repository("backup")
    meta = _repo_shard_meta(master, "snap1")[0]
    blob = sorted(next(iter(meta["segments"].values())).values())[0]
    repo.shard_container("logs", 0).delete_blob(blob)
    assert repo.verify_integrity() != []

    rep = cluster.call(master.health_report, "repository_integrity")
    ind = rep["indicators"]["repository_integrity"]
    assert ind["status"] == "red", ind
    assert any(d["id"] == "repository_integrity:corruption"
               for d in ind.get("diagnosis", []))


# ------------------------------------------------------------- determinism

def _replay_scenario(tmp_path, tag):
    """One full snapshot-under-load + cancel + restore story, returning
    everything observable that must be identical across same-seed
    replays (uuids excluded by design: they name, never steer)."""
    cluster = SimDataCluster(3, tmp_path / tag, seed=71)
    master = cluster.stabilise()
    _put_repo(cluster, master, "backup")
    cluster.call(master.create_index, "logs",
                 number_of_shards=2, number_of_replicas=1)
    cluster.run_for(30)
    _index_some_docs(cluster, master, n=25)
    acked = []
    _staggered_bulks(cluster, master, acked, rounds=8, gap=0.3)
    snap = cluster.call(master.create_snapshot, "backup", "replay-snap",
                        {"indices": "logs"})
    cluster.run_for(30)
    cluster.call(master.refresh)
    resp = cluster.call(master.restore_snapshot, "backup", "replay-snap",
                        {"indices": "logs", "rename_pattern": "logs",
                         "rename_replacement": "logs_r"})
    assert resp["accepted"] is True
    cluster.run_for(60)
    cluster.call(master.refresh)
    shard_meta = _repo_shard_meta(master, "replay-snap")
    return {
        "state": snap["snapshot"]["state"],
        "acked": sorted(acked),
        "live": _sorted_hits(cluster, master, "logs"),
        "restored": _sorted_hits(cluster, master, "logs_r"),
        "bytes": [(m["total_bytes"], m["uploaded_bytes"],
                   m["skipped_bytes"], m["consistency_point"],
                   (m.get("translog") or {}).get("ops"))
                  for m in shard_meta],
    }


def test_same_seed_replays_byte_identical(tmp_path):
    """The whole snapshot/restore story — upload byte counts,
    consistency points, acked sets, restored result sets — replays
    identically from the same queue seed."""
    a = _replay_scenario(tmp_path, "run-a")
    b = _replay_scenario(tmp_path, "run-b")
    assert a == b
