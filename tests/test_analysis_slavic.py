"""analysis-stempel (Polish) + analysis-ukrainian plugins (ref:
plugins/analysis-stempel/.../AnalysisStempelPlugin.java,
plugins/analysis-ukrainian/.../AnalysisUkrainianPlugin.java) —
installable plugins registering the ``polish``/``ukrainian`` analyzers
and stem filters; stemming is a disclosed algorithmic approximation of
the reference's table/dictionary stemmers, so tests assert conflation
classes (inflected forms meeting at one stem), not exact stems."""

import os

import pytest

from elasticsearch_tpu.analysis import analyzers as an
from elasticsearch_tpu.analysis.slavic import polish_stem, ukrainian_stem
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import PluginsService
from elasticsearch_tpu.plugins import main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def plugins(tmp_path):
    pd = str(tmp_path / "plugins")
    for name in ("analysis_stempel", "analysis_ukrainian"):
        plugin_cli(["install", os.path.join(REPO_ROOT, "plugins_src", name),
                    "--plugins-dir", pd])
    svc = PluginsService(pd)
    svc.load_all()
    yield pd
    for flt in ("polish_stem", "ukrainian_stem"):
        an._TOKEN_FILTERS.pop(flt, None)
    for name in ("polish", "ukrainian"):
        an.PLUGIN_ANALYZERS.pop(name, None)


# ---------------------------------------------------------------------------
# stemmer conflation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("forms", [
    # noun declension: 'książka' (book)
    ["książka", "książki", "książkę", "książkami"],
    # noun: 'nauczyciel' (teacher)
    ["nauczyciel", "nauczyciela", "nauczycielem", "nauczycielowi"],
    # adjective: 'dobry' (good)
    ["dobry", "dobra", "dobre", "dobrego", "dobremu", "dobrych"],
    # verb past forms: 'pracować' (to work)
    ["pracowałem", "pracowałeś", "pracowała", "pracowali"],
])
def test_polish_conflation(forms):
    stems = {polish_stem(w) for w in forms}
    assert len(stems) == 1, (forms, stems)


def test_polish_short_words_untouched():
    assert polish_stem("do") == "do"
    assert polish_stem("kot") == "kot"


@pytest.mark.parametrize("forms", [
    # noun: 'книга' (book)
    ["книга", "книги", "книгу", "книгою", "книгами"],
    # adjective: 'український' (Ukrainian)
    ["український", "українського", "українська", "українські"],
    # verb: 'читати' (to read) incl. reflexive
    ["читати", "читала", "читали", "читалася"],
])
def test_ukrainian_conflation(forms):
    stems = {ukrainian_stem(w) for w in forms}
    assert len(stems) == 1, (forms, stems)


# ---------------------------------------------------------------------------
# end-to-end through a node
# ---------------------------------------------------------------------------


def test_polish_search_through_node(tmp_path, plugins):
    node = Node(settings=Settings.from_dict({"path": {"plugins": plugins}}),
                data_path=str(tmp_path / "data"))
    try:
        c = node.rest_controller
        st, r = c.dispatch("PUT", "/pl", None, {
            "mappings": {"properties": {
                "body": {"type": "text", "analyzer": "polish"}}}})
        assert st == 200, r
        c.dispatch("PUT", "/pl/_doc/1", None,
                   {"body": "Nauczyciel czyta książki w bibliotece"})
        c.dispatch("POST", "/pl/_refresh", None, None)
        # inflected query form matches the indexed form via stemming
        st, r = c.dispatch("POST", "/pl/_search", None,
                           {"query": {"match": {"body": "książka"}}})
        assert st == 200 and r["hits"]["total"]["value"] == 1
        # stopwords drop out of the analysis chain
        st, r = c.dispatch(
            "GET", "/pl/_analyze", None,
            {"analyzer": "polish", "text": "w bibliotece"})
        assert st == 200
        assert [t["token"] for t in r["tokens"]] == [
            polish_stem("bibliotece")]
    finally:
        node.close()


def test_ukrainian_search_through_node(tmp_path, plugins):
    node = Node(settings=Settings.from_dict({"path": {"plugins": plugins}}),
                data_path=str(tmp_path / "data"))
    try:
        c = node.rest_controller
        st, r = c.dispatch("PUT", "/uk", None, {
            "mappings": {"properties": {
                "body": {"type": "text", "analyzer": "ukrainian"}}}})
        assert st == 200, r
        c.dispatch("PUT", "/uk/_doc/1", None,
                   {"body": "Студенти читали українські книги"})
        c.dispatch("POST", "/uk/_refresh", None, None)
        st, r = c.dispatch("POST", "/uk/_search", None,
                           {"query": {"match": {"body": "книга"}}})
        assert st == 200 and r["hits"]["total"]["value"] == 1
        # apostrophe variants normalize: м’яко (U+2019) matches м'яко
        st, r = c.dispatch(
            "GET", "/uk/_analyze", None,
            {"analyzer": "ukrainian", "text": "м’яко"})
        assert st == 200
        st2, r2 = c.dispatch(
            "GET", "/uk/_analyze", None,
            {"analyzer": "ukrainian", "text": "м'яко"})
        assert [t["token"] for t in r["tokens"]] == \
            [t["token"] for t in r2["tokens"]]
    finally:
        node.close()


def test_stem_filters_usable_in_custom_analyzers(plugins):
    reg = an.AnalysisRegistry(Settings.from_dict({
        "analysis": {"analyzer": {"my_pl": {
            "type": "custom", "tokenizer": "standard",
            "filter": ["lowercase", "polish_stem"]}}}}))
    terms = reg.get("my_pl").terms("Książki nauczyciela")
    assert terms == [polish_stem("książki"), polish_stem("nauczyciela")]
