"""Deterministic harness: task queue, linearizability checker, and the
flagship check — the cluster acting as a linearizable register under
random disruptions (ref: LinearizabilityChecker.java:53,230 +
CoordinatorTests safety assertions)."""

from dataclasses import replace

import pytest

from elasticsearch_tpu.testing.deterministic import (
    BLACKHOLE,
    DISCONNECTED,
    DeterministicTaskQueue,
    History,
    RegisterSpec,
    SequentialSpec,
    check_linearizable,
)

from test_coordination import SimCluster  # noqa: E402


# ------------------------------------------------------------ task queue

def test_virtual_time_advances_to_deferred_tasks():
    q = DeterministicTaskQueue(seed=1)
    fired = []
    q.schedule(5.0, lambda: fired.append("late"))
    q.schedule(1.0, lambda: fired.append("early"))
    q.schedule(0.0, lambda: fired.append("now"))
    q.run_until_idle()
    assert fired == ["now", "early", "late"]
    assert q.now() == 5.0


def test_cancellation():
    q = DeterministicTaskQueue(seed=1)
    fired = []
    c = q.schedule(1.0, lambda: fired.append("x"))
    c.cancel()
    q.run_until_idle()
    assert fired == []


def test_seeded_interleaving_is_reproducible():
    def run(seed):
        q = DeterministicTaskQueue(seed=seed)
        order = []
        for i in range(10):
            q.schedule(0.0, lambda i=i: order.append(i))
        q.run_all_runnable()
        return order

    assert run(3) == run(3)
    assert run(3) != list(range(10)) or run(4) != run(3)


def test_run_for_respects_window():
    q = DeterministicTaskQueue(seed=0)
    fired = []
    q.schedule(1.0, lambda: fired.append(1))
    q.schedule(100.0, lambda: fired.append(2))
    q.run_for(10.0)
    assert fired == [1]
    assert q.now() == 10.0


# ------------------------------------------------- linearizability checker

def test_sequential_history_ok():
    h = History()
    op = h.invoke(0, ("write", 5))
    h.respond(0, op, "ok")
    op = h.invoke(0, ("read", None))
    h.respond(0, op, 5)
    assert check_linearizable(RegisterSpec(), h)


def test_stale_read_rejected():
    h = History()
    w1 = h.invoke(0, ("write", 1))
    h.respond(0, w1, "ok")
    w2 = h.invoke(0, ("write", 2))
    h.respond(0, w2, "ok")
    r = h.invoke(1, ("read", None))
    h.respond(1, r, 1)  # reads the overwritten value — not linearizable
    assert not check_linearizable(RegisterSpec(), h)


def test_concurrent_ops_may_reorder():
    h = History()
    # write(1) and write(2) concurrent; read observes 1 then later 2:
    w1 = h.invoke(0, ("write", 1))
    w2 = h.invoke(1, ("write", 2))
    r1 = h.invoke(2, ("read", None))
    h.respond(2, r1, 2)
    h.respond(1, w2, "ok")
    h.respond(0, w1, "ok")
    r2 = h.invoke(2, ("read", None))
    h.respond(2, r2, 1)  # w1 linearized after w2 — legal (concurrent)
    assert check_linearizable(RegisterSpec(), h)


def test_read_before_any_write():
    h = History()
    r = h.invoke(0, ("read", None))
    h.respond(0, r, None)
    w = h.invoke(0, ("write", 3))
    h.respond(0, w, "ok")
    assert check_linearizable(RegisterSpec(), h)


# ----------------------------------- cluster-as-register under disruption

class MaybeRegisterSpec(SequentialSpec):
    """Register whose state is the set of possible values: writes that
    timed out ("maybe") may or may not have been applied (the sound way
    to complete a history with dropped responses)."""

    def initial_state(self):
        return frozenset([None])

    def apply(self, state, inp, outp):
        kind, val = inp
        if kind == "write":
            if outp == "ok":
                return (True, frozenset([val]))
            if outp == "maybe":
                return (True, state | {val})
            return (False, state)
        if kind == "read":
            return (outp in state, frozenset([outp]))
        return (False, state)

    def fingerprint(self, state):
        return state


def _register_ops(cluster, history, process, value, kind):
    """Submit one register op through the current leader, recording
    invoke/response in the history. Reads go through a full publication
    (read-through-quorum) so they are linearizable by construction —
    the test verifies the implementation delivers that."""
    leaders = cluster.leaders()
    if not leaders:
        return
    leader = leaders[0]
    op = history.invoke(process, (kind, value))
    seen = {}

    def update(state):
        seen["val"] = state.metadata.persistent_settings.get("reg")
        settings = dict(state.metadata.persistent_settings)
        if kind == "write":
            settings["reg"] = value
        settings["nonce"] = settings.get("nonce", 0) + 1
        return state.with_(metadata=replace(
            state.metadata, persistent_settings=settings,
            version=state.metadata.version + 1))

    def on_done(err):
        if err is None:
            history.respond(process, op,
                            "ok" if kind == "write" else seen["val"])
        elif kind == "write":
            history.respond(process, op, "maybe")
        else:
            history.respond(process, op, "__failed__")

    leader.submit_state_update(f"register-{kind}", update, on_done=on_done)


def _strip_failed_reads(history):
    failed = {e.op_id for e in history.events
              if e.kind == "response" and e.value == "__failed__"}
    history.events = [e for e in history.events if e.op_id not in failed]


@pytest.mark.parametrize("seed", [2, 21])
def test_cluster_register_linearizable_under_disruption(seed):
    cluster = SimCluster(3, seed=seed)
    cluster.stabilise()
    history = History()
    rng = cluster.queue.random
    value = 0
    for round_ in range(8):
        for _ in range(rng.randrange(1, 4)):
            value += 1
            kind = rng.choice(["write", "write", "read"])
            _register_ops(cluster, history, process=rng.randrange(3),
                          value=value if kind == "write" else None,
                          kind=kind)
            cluster.run_for(rng.uniform(0.1, 3.0))
        if round_ % 3 == 1:
            victim = rng.choice(cluster.nodes)
            cluster.network.isolate(
                victim, cluster.nodes,
                mode=rng.choice([BLACKHOLE, DISCONNECTED]))
            cluster.run_for(rng.uniform(5, 40))
            cluster.network.heal()
            cluster.run_for(rng.uniform(5, 40))
    cluster.network.heal()
    cluster.run_for(240)
    _strip_failed_reads(history)
    history.complete_pending(lambda inp: "maybe" if inp[0] == "write"
                             else "__failed__")
    _strip_failed_reads(history)
    assert check_linearizable(MaybeRegisterSpec(), history), \
        f"history not linearizable: {history.events}"
