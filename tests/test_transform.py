"""Transform tests: pivot/latest compute, batch vs continuous checkpoints,
preview, REST (model: the reference's TransformIndexerTests /
TransformConfigTests)."""

import tempfile

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.node import Node

SALES = [
    {"store": "berlin", "item": "shirt", "price": 10.0, "ts": "2024-01-01"},
    {"store": "berlin", "item": "shoes", "price": 50.0, "ts": "2024-01-02"},
    {"store": "paris", "item": "shirt", "price": 12.0, "ts": "2024-01-02"},
    {"store": "paris", "item": "hat", "price": 8.0, "ts": "2024-01-03"},
    {"store": "berlin", "item": "hat", "price": 9.0, "ts": "2024-01-04"},
]


@pytest.fixture()
def node():
    n = Node(data_path=tempfile.mkdtemp())
    idx = n.indices_service.create_index("sales", mappings={"properties": {
        "store": {"type": "keyword"}, "item": {"type": "keyword"},
        "price": {"type": "float"}, "ts": {"type": "date"}}})
    for i, d in enumerate(SALES):
        idx.index_doc(str(i), d)
    idx.refresh()
    yield n
    n.close()


PIVOT_CONFIG = {
    "source": {"index": "sales"},
    "dest": {"index": "sales_by_store"},
    "pivot": {
        "group_by": {"store": {"terms": {"field": "store"}}},
        "aggregations": {"revenue": {"sum": {"field": "price"}},
                         "avg_price": {"avg": {"field": "price"}}}},
}


def search_dest(node, index):
    r = node.search_service.search(index, {"size": 100})
    return {h["_source"]["store"]: h["_source"] for h in r["hits"]["hits"]}


def test_batch_pivot(node):
    ts = node.transform_service
    ts.put_transform("by-store", PIVOT_CONFIG)
    ts.start_transform("by-store")   # batch: runs to completion
    by_store = search_dest(node, "sales_by_store")
    assert by_store["berlin"]["revenue"] == pytest.approx(69.0)
    assert by_store["paris"]["revenue"] == pytest.approx(20.0)
    assert by_store["berlin"]["avg_price"] == pytest.approx(23.0)
    st = ts.get_stats("by-store")
    assert st["state"] == "stopped"           # batch completes
    assert st["documents_indexed"] == 2
    assert st["checkpoint"] == 1


def test_multi_group_by(node):
    ts = node.transform_service
    cfg = {
        "source": {"index": "sales"},
        "dest": {"index": "by_store_item"},
        "pivot": {"group_by": {
            "store": {"terms": {"field": "store"}},
            "item": {"terms": {"field": "item"}}},
            "aggregations": {"n": {"value_count": {"field": "price"}}}},
    }
    ts.put_transform("bsi", cfg)
    ts.start_transform("bsi")
    r = node.search_service.search("by_store_item", {"size": 100})
    rows = {(h["_source"]["store"], h["_source"]["item"]) for h in
            r["hits"]["hits"]}
    assert ("berlin", "shirt") in rows and ("paris", "hat") in rows
    assert len(rows) == 5


def test_continuous_transform_checkpoints(node):
    ts = node.transform_service
    cfg = dict(PIVOT_CONFIG, sync={"time": {"field": "ts"}},
               dest={"index": "cont_dest"})
    ts.put_transform("cont", cfg)
    ts.start_transform("cont")
    assert ts.get_stats("cont")["state"] == "started"  # continuous stays up
    ts.trigger("cont")
    assert search_dest(node, "cont_dest")["berlin"]["revenue"] == \
        pytest.approx(69.0)
    # new data arrives; next trigger updates the bucket doc in place
    idx = node.indices_service.get("sales")
    idx.index_doc("5", {"store": "berlin", "item": "coat", "price": 31.0,
                        "ts": "2024-01-05"})
    idx.refresh()
    ts.trigger("cont")
    assert search_dest(node, "cont_dest")["berlin"]["revenue"] == \
        pytest.approx(100.0)
    st = ts.get_stats("cont")
    assert st["checkpoint"] == 2
    ts.stop_transform("cont")
    assert ts.get_stats("cont")["state"] == "stopped"


def test_latest_transform(node):
    ts = node.transform_service
    cfg = {"source": {"index": "sales"},
           "dest": {"index": "latest_per_store"},
           "latest": {"unique_key": ["store"], "sort": "ts"}}
    ts.put_transform("latest", cfg)
    ts.start_transform("latest")
    by_store = search_dest(node, "latest_per_store")
    assert by_store["berlin"]["item"] == "hat"     # 2024-01-04 newest
    assert by_store["paris"]["item"] == "hat"      # 2024-01-03 newest


def test_preview_does_not_write(node):
    ts = node.transform_service
    out = ts.preview(PIVOT_CONFIG)
    assert len(out["preview"]) == 2
    assert not node.indices_service.has("sales_by_store")


def test_validation(node):
    ts = node.transform_service
    with pytest.raises(IllegalArgumentException):
        ts.put_transform("bad1", {"source": {"index": "s"},
                                  "dest": {"index": "d"}})
    with pytest.raises(IllegalArgumentException):
        ts.put_transform("bad2", {
            "source": {"index": "s"}, "dest": {"index": "d"},
            "pivot": {"group_by": {"a": {"terms": {"field": "x"}}}},
            "latest": {"unique_key": ["k"], "sort": "t"}})


def test_delete_running_rejected(node):
    ts = node.transform_service
    cfg = dict(PIVOT_CONFIG, sync={"time": {"field": "ts"}},
               dest={"index": "d2"})
    ts.put_transform("run", cfg)
    ts.start_transform("run")
    with pytest.raises(IllegalArgumentException):
        ts.delete_transform("run")
    ts.delete_transform("run", force=True)
    with pytest.raises(ResourceNotFoundException):
        ts.get_stats("run")


def test_rest_roundtrip(node):
    c = node.rest_controller
    s, r = c.dispatch("PUT", "/_transform/t1", None, PIVOT_CONFIG)
    assert s == 200
    s, r = c.dispatch("GET", "/_transform/t1", None, None)
    assert s == 200 and r["transforms"][0]["id"] == "t1"
    s, r = c.dispatch("POST", "/_transform/_preview", None, PIVOT_CONFIG)
    assert s == 200 and len(r["preview"]) == 2
    s, r = c.dispatch("POST", "/_transform/t1/_start", None, None)
    assert s == 200
    s, r = c.dispatch("GET", "/_transform/t1/_stats", None, None)
    assert r["transforms"][0]["documents_indexed"] == 2
    s, r = c.dispatch("DELETE", "/_transform/t1", None, None)
    assert s == 200
