"""Engine-level device observability (telemetry/engine.py + the HBM /
device-cache accounting in ops/device.py): the compile tracker's
shape-discipline contract, HBM slab accounting vs live DeviceSegments,
filter-mask LRU eviction visibility, and the cluster engine-stats
fan-out."""

import numpy as np
import pytest

import elasticsearch_tpu.ops.device as device_mod
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops.device import DeviceSegment
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.search.queries import parse_query
from elasticsearch_tpu.search.searcher import ShardSearcher
from elasticsearch_tpu.telemetry.engine import TRACKER

MAPPINGS = {
    "properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "n": {"type": "long"},
    }
}

WORDS = ["alpha", "beta", "gamma", "delta", "fox", "dog", "wolf",
         "lake", "hill", "tree"]


def build_segment(n_docs=60, name="seg0", seed=3):
    rng = np.random.default_rng(seed)
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i in range(n_docs):
        w.add(svc.parse(str(i), {
            "body": " ".join(rng.choice(WORDS, 6)),
            "tag": str(rng.choice(["red", "green", "blue"])),
            "n": int(i)}))
    return w.build(name), svc


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

def test_hbm_by_class_sums_to_total():
    seg, _svc = build_segment()
    dev = DeviceSegment(seg)
    by_class = dev.hbm_bytes_by_class()
    assert set(by_class) == set(device_mod.HBM_SLAB_CLASSES)
    assert dev.hbm_bytes() == sum(by_class.values())
    assert by_class["postings"] > 0
    assert by_class["norms"] > 0
    assert by_class["live_mask"] == dev.n_docs_padded  # 1 byte per doc


def test_cache_rollup_equals_sum_over_live_segments():
    """The acceptance invariant: the engine section's HBM bytes equal
    the sum over live DeviceSegments' slab sizes."""
    cache = DeviceSegmentCache()
    segs = [build_segment(40, f"hbm{i}", seed=i)[0] for i in range(3)]
    devs = [cache.get(s) for s in segs]
    stats = cache.hbm_stats()
    assert stats["segments"] == 3
    assert stats["total_bytes"] == sum(d.hbm_bytes() for d in devs)
    assert stats["peak_bytes"] >= stats["total_bytes"]
    # eviction returns bytes AND the peak watermark remembers the high
    cache.evict([segs[0].name])
    stats2 = cache.hbm_stats()
    assert stats2["total_bytes"] == sum(d.hbm_bytes() for d in devs[1:])
    assert stats2["total_bytes"] < stats["total_bytes"]
    assert stats2["peak_bytes"] >= stats["total_bytes"]


def test_filter_mask_bytes_show_up_in_accounting():
    seg, _svc = build_segment()
    dev = DeviceSegment(seg)
    before = dev.hbm_bytes_by_class()["filter_masks"]
    dev.filter_mask("body", ("fox",))
    after = dev.hbm_bytes_by_class()["filter_masks"]
    assert before == 0 and after == dev.n_docs_padded


# ---------------------------------------------------------------------------
# filter-mask LRU eviction (satellite: fill past the cap)
# ---------------------------------------------------------------------------

def test_filter_mask_lru_eviction(monkeypatch):
    monkeypatch.setattr(device_mod, "FILTER_MASK_CACHE_MAX", 4)
    seg, _svc = build_segment()
    dev = DeviceSegment(seg)
    # fill past the cap with distinct single-term keys
    for i, word in enumerate(WORDS[:6]):
        dev.filter_mask("body", (word,))
    cs = dev.cache_stats()["filter_mask"]
    assert cs["misses"] == 6
    assert cs["evictions"] == 2
    assert cs["entries"] == 4
    bytes_at_cap = cs["bytes"]
    # byte accounting decreases when the cap tightens further
    monkeypatch.setattr(device_mod, "FILTER_MASK_CACHE_MAX", 2)
    dev.filter_mask("body", ("lake", "hill"))     # new key -> trims to 2
    cs = dev.cache_stats()["filter_mask"]
    assert cs["entries"] == 2
    assert cs["bytes"] < bytes_at_cap
    assert cs["evictions"] == 2 + 3              # 5 total now
    # the oldest keys were evicted: re-querying one is a miss that
    # re-populates, and the SAME query straight after is a hit
    misses0, hits0 = cs["misses"], cs["hits"]
    m1 = dev.filter_mask("body", (WORDS[0],))
    cs = dev.cache_stats()["filter_mask"]
    assert cs["misses"] == misses0 + 1
    m2 = dev.filter_mask("body", (WORDS[0],))
    cs = dev.cache_stats()["filter_mask"]
    assert cs["hits"] == hits0 + 1
    assert m1[0] is m2[0]                        # identical device column
    np.testing.assert_array_equal(m1[1], m2[1])


# ---------------------------------------------------------------------------
# compile tracker: shape discipline
# ---------------------------------------------------------------------------

@pytest.fixture()
def searcher():
    seg, svc = build_segment(80, "cmp0", seed=11)
    return ShardSearcher([seg], svc, DeviceSegmentCache())


def test_fixed_shape_workload_compile_count_flat(searcher):
    """A fixed-shape query workload must show engine.compile.count flat
    after warmup — THE shape-discipline contract."""
    q = parse_query({"match": {"body": "fox"}})
    sort = [{"n": "desc"}]
    searcher.query_phase(q, 23, sort=sort)        # warmup (may compile)
    warm = TRACKER.total_compiles()
    for _ in range(4):
        searcher.query_phase(q, 23, sort=sort)
    assert TRACKER.total_compiles() == warm, (
        "identical searches recompiled a kernel:\n"
        f"{TRACKER.to_dict()}")


def test_bucket_busting_workload_compile_count_grows(searcher):
    """A deliberately bucket-busting workload (a fresh static k per
    query -> a fresh jit shape key per query) must be VISIBLE as a
    growing compile count — the recompile-storm signal."""
    q = parse_query({"match": {"body": "fox"}})
    sort = [{"n": "desc"}]
    # distinctive k values no other test plausibly used in this process
    sizes = [311, 313, 317, 331]
    before = TRACKER.total_compiles()
    calls_before = TRACKER.to_dict().get("masked_topk", {}).get("calls", 0)
    for k in sizes:
        searcher.query_phase(q, k, sort=sort)
    grew = TRACKER.total_compiles() - before
    assert grew >= len(sizes), (
        f"expected >= {len(sizes)} new compiles, saw {grew}")
    # and the per-kernel table attributes them: same kernel, new shapes
    entry = TRACKER.to_dict()["masked_topk"]
    assert entry["calls"] > calls_before
    assert entry["last_compile"]["trigger"]      # diff vs previous key
    assert entry["shapes_seen"] >= len(sizes)


def test_compile_table_records_kernel_shape_and_ms():
    from elasticsearch_tpu.ops import topk as topk_ops
    import jax.numpy as jnp
    before = TRACKER.compiles_of("masked_topk")
    s = jnp.asarray(np.random.default_rng(0)
                    .random(257).astype(np.float32))
    m = jnp.asarray(np.ones(257, bool))
    topk_ops.masked_topk(s, m, 19)               # fresh shape
    topk_ops.masked_topk(s, m, 19)               # repeat: no new compile
    assert TRACKER.compiles_of("masked_topk") == before + 1
    entry = TRACKER.to_dict()["masked_topk"]
    keys = [sh["key"] for sh in entry["shapes"]]
    assert any("scores[257]float32" in k and "k=19" in k for k in keys)
    assert entry["cum_ms"] > 0


def test_compile_metrics_reach_registered_sinks():
    from elasticsearch_tpu.ops import topk as topk_ops
    from elasticsearch_tpu.telemetry import Telemetry
    import jax.numpy as jnp
    tele = Telemetry(node="engine-test")
    s = jnp.asarray(np.random.default_rng(1)
                    .random(263).astype(np.float32))
    m = jnp.asarray(np.ones(263, bool))
    topk_ops.masked_topk(s, m, 21)               # fresh shape
    assert tele.metrics.get_value("engine.compile.count") >= 1
    assert tele.metrics.get_value("engine.compile.ms") > 0


# ---------------------------------------------------------------------------
# plan / bound-plan cache counters
# ---------------------------------------------------------------------------

def test_plan_and_bound_plan_cache_counters(searcher):
    q = parse_query({"match": {"body": "dog"}})
    searcher.query_phase(q, 10, cache_key="ck1")
    assert searcher.cache.plan_cache_misses >= 1
    hits0 = searcher.cache.plan_cache_hits
    searcher.query_phase(q, 10, cache_key="ck1")
    assert searcher.cache.plan_cache_hits == hits0 + 1
    caches = searcher.cache.cache_stats()
    assert caches["bound_plan"]["misses"] >= 1
    assert caches["bound_plan"]["hits"] >= 1
    assert caches["plan"]["entries"] >= 1


# ---------------------------------------------------------------------------
# tracer span-retention ring (satellite)
# ---------------------------------------------------------------------------

def test_tracer_span_ring_bounds_retention():
    from elasticsearch_tpu.telemetry.tracing import Tracer
    t = Tracer(node="ring", max_spans_per_trace=4)
    root = t.start_span("root")
    for i in range(6):
        t.start_span(f"child-{i}", parent=root).finish()
    root.finish()
    tr = t.trace(root.trace_id)
    assert len(tr["spans"]) == 4
    assert tr["dropped_spans"] == 3              # 7 finished, 4 kept
    names = {s["name"] for s in tr["spans"]}
    assert "child-0" not in names                # oldest dropped first
    assert "root" in names                       # newest survive
    summary = t.recent_traces()[0]
    assert summary["dropped_spans"] == 3
    assert t.dropped_spans_total == 3


def test_recent_traces_size_and_from_paging():
    from elasticsearch_tpu.telemetry.tracing import Tracer
    t = Tracer(node="page")
    ids = []
    for i in range(5):
        s = t.start_span(f"op-{i}")
        ids.append(s.trace_id)
        s.finish()
    page0 = t.recent_traces(limit=2, offset=0)
    page1 = t.recent_traces(limit=2, offset=2)
    assert [p["trace_id"] for p in page0] == [ids[4], ids[3]]
    assert [p["trace_id"] for p in page1] == [ids[2], ids[1]]


def test_sub_ms_histogram_buckets_resolve_device_stages():
    from elasticsearch_tpu.telemetry.metrics import Histogram
    h = Histogram()
    h.observe(0.002)    # a 2µs readback no longer collapses
    h.observe(0.03)
    h.observe(0.3)
    b = h.to_dict()["buckets"]
    assert b["le_0.001"] == 0
    assert b["le_0.005"] == 1
    assert b["le_0.05"] == 2
    assert b["le_0.5"] == 3


# ---------------------------------------------------------------------------
# REST surfaces: the acceptance invariant through `GET /_nodes/stats`
# ---------------------------------------------------------------------------

@pytest.fixture()
def node(tmp_path):
    from elasticsearch_tpu.node import Node
    n = Node(data_path=str(tmp_path / "node"))
    yield n
    n.close()


def _seed_index(node, n_docs=8):
    d = node.rest_controller.dispatch
    assert d("PUT", "/obs", None,
             {"settings": {"index.number_of_shards": 2}})[0] == 200
    for i in range(n_docs):
        d("PUT", f"/obs/_doc/{i}", {},
          {"body": f"quick brown fox {i}", "n": i})
    d("POST", "/obs/_refresh", None, None)


def test_nodes_stats_engine_hbm_equals_live_device_segments(node):
    _seed_index(node)
    d = node.rest_controller.dispatch
    st, _ = d("POST", "/obs/_search", {},
              {"query": {"match": {"body": "fox"}}, "sort": [{"n": "desc"}]})
    assert st == 200
    st, stats = d("GET", "/_nodes/stats", {}, None)
    assert st == 200
    eng = next(iter(stats["nodes"].values()))["engine"]
    cache = node.indices_service.device_cache
    expected = sum(dev.hbm_bytes()
                   for _v, dev in cache._cache.values())
    assert eng["hbm"]["total_bytes"] == expected > 0
    assert eng["hbm"]["total_bytes"] == sum(
        eng["hbm"]["by_class"].values())
    assert eng["hbm"]["peak_bytes"] >= eng["hbm"]["total_bytes"]
    assert eng["compile"]["count"] >= 0
    assert set(eng["caches"]) >= {"filter_mask", "bound_plan", "plan"}
    # per-index slice agrees (single index: same resident segments)
    st, idx_stats = d("GET", "/obs/_stats", {}, None)
    assert idx_stats["indices"]["obs"]["engine"]["hbm"]["total_bytes"] \
        == expected
    assert sum(idx_stats["indices"]["obs"]["engine"]["hbm"]
               ["shard_bytes"]) == expected


def test_kernels_endpoint_stable_count_until_new_shape_bucket(node):
    _seed_index(node)
    d = node.rest_controller.dispatch
    body = {"query": {"match": {"body": "fox"}}, "sort": [{"n": "desc"}],
            "size": 5}
    d("POST", "/obs/_search", {}, body)          # warmup
    st, k1 = d("GET", "/_kernels", {}, None)
    assert st == 200
    for _ in range(3):
        d("POST", "/obs/_search", {}, body)
    st, k2 = d("GET", "/_kernels", {}, None)
    assert k2["totals"]["count"] == k1["totals"]["count"], (
        "repeated same-shape searches must not compile")
    assert k2["totals"]["calls"] > k1["totals"]["calls"]
    # a new shape bucket (fresh static k) increments the count
    d("POST", "/obs/_search", {},
      {**body, "size": 347})
    st, k3 = d("GET", "/_kernels", {}, None)
    assert k3["totals"]["count"] > k2["totals"]["count"]
    assert "masked_topk" in k3["kernels"]


# ---------------------------------------------------------------------------
# cluster fan-out
# ---------------------------------------------------------------------------

def test_cluster_engine_stats_fan_out(tmp_path):
    from test_cluster_node import SimDataCluster, _index_some_docs
    cluster = SimDataCluster(3, tmp_path, seed=23)
    master = cluster.stabilise()
    cluster.call(master.create_index, "logs", 2, 1)
    cluster.run_for(30)
    _index_some_docs(cluster, master)
    # a search populates the data nodes' device caches
    r = cluster.call(master.search, "logs",
                     {"query": {"match": {"body": "fox"}}})
    assert r["hits"]["total"]["value"] > 0
    stats = cluster.call(master.nodes_engine_stats)
    assert len(stats["nodes"]) == 3
    per_node = [s for s in stats["nodes"].values() if "error" not in s]
    assert per_node, stats
    assert stats["total_hbm_bytes"] == sum(
        s["hbm"]["total_bytes"] for s in per_node)
    assert stats["total_hbm_bytes"] > 0          # something is resident
    for s in per_node:
        assert s["hbm"]["total_bytes"] == sum(
            s["hbm"]["by_class"].values())
        assert "compile" in s and "caches" in s
