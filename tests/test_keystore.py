"""Secure-settings keystore + consistent-settings tests (ref:
KeyStoreWrapperTests, ConsistentSettingsServiceTests)."""

import json

import pytest

from elasticsearch_tpu.common.errors import SettingsException
from elasticsearch_tpu.common.keystore import (
    SEED_SETTING,
    ConsistentSettingsService,
    KeyStore,
    SecureSetting,
    main as keystore_cli,
)
from elasticsearch_tpu.common.settings import Settings


def test_create_load_roundtrip(tmp_path):
    path = str(tmp_path / "elasticsearch.keystore")
    ks = KeyStore.create(path, "s3cret")
    ks.set_string("xpack.security.token.key", "hunter2")
    ks.save("s3cret")

    loaded = KeyStore(path).load("s3cret")
    assert loaded.get_string("xpack.security.token.key") == "hunter2"
    assert loaded.has(SEED_SETTING)          # auto-seeded, as the reference
    assert "xpack.security.token.key" in loaded.setting_names()


def test_wrong_password_rejected(tmp_path):
    path = str(tmp_path / "ks")
    KeyStore.create(path, "right")
    with pytest.raises(SettingsException, match="incorrect|corrupted"):
        KeyStore(path).load("wrong")


def test_tamper_detected(tmp_path):
    path = str(tmp_path / "ks")
    KeyStore.create(path, "")
    with open(path) as f:
        env = json.load(f)
    ct = bytearray.fromhex("00") * 4
    import base64
    raw = bytearray(base64.b64decode(env["ciphertext"]))
    raw[0] ^= 0xFF
    env["ciphertext"] = base64.b64encode(bytes(raw)).decode()
    with open(path, "w") as f:
        json.dump(env, f)
    with pytest.raises(SettingsException, match="corrupted|incorrect"):
        KeyStore(path).load("")
    assert ct is not None


def test_values_encrypted_at_rest(tmp_path):
    path = str(tmp_path / "ks")
    ks = KeyStore.create(path, "pw")
    ks.set_string("cloud.secret", "super-sensitive-value")
    ks.save("pw")
    blob = open(path, "rb").read()
    assert b"super-sensitive-value" not in blob
    assert b"cloud.secret" not in blob


def test_secure_setting_refuses_plain_settings(tmp_path):
    s = SecureSetting("repo.s3.client.secret_key")
    settings = Settings.from_dict({"repo": {"s3": {"client": {
        "secret_key": "leaked"}}}})
    with pytest.raises(SettingsException, match="secure setting"):
        s.get(settings, None)
    ks = KeyStore.create(str(tmp_path / "ks"), "")
    ks.set_string("repo.s3.client.secret_key", "ok-value")
    assert s.get(Settings.EMPTY, ks) == "ok-value"


def test_consistent_hashes_match_and_mismatch(tmp_path):
    a = KeyStore.create(str(tmp_path / "a"), "")
    b = KeyStore.create(str(tmp_path / "b"), "")
    a.set_string("secret.shared", "same-value")
    b.set_string("secret.shared", "same-value")
    svc_a = ConsistentSettingsService(a, ["secret.shared"])
    svc_b = ConsistentSettingsService(b, ["secret.shared"])
    published = svc_a.compute_hashes()
    assert "secret.shared" in published
    assert svc_b.verify(published) is None

    b.set_string("secret.shared", "DIFFERENT")
    assert "does NOT match" in svc_b.verify(published)

    b.remove("secret.shared")
    assert "missing" in svc_b.verify(published)


def test_cli(tmp_path, capsys):
    path = str(tmp_path / "cli.keystore")
    assert keystore_cli(["create", "--path", path, "--password", "pw"]) == 0
    assert keystore_cli(["add", "my.setting", "v1", "--path", path,
                         "--password", "pw"]) == 0
    assert keystore_cli(["list", "--path", path, "--password", "pw"]) == 0
    out = capsys.readouterr().out
    assert "my.setting" in out
    assert keystore_cli(["show", "my.setting", "--path", path,
                         "--password", "pw"]) == 0
    assert "v1" in capsys.readouterr().out


def test_node_prefers_keystore_bootstrap_password(tmp_path):
    from elasticsearch_tpu.common.keystore import KEYSTORE_FILENAME
    from elasticsearch_tpu.node import Node

    data = tmp_path / "node"
    data.mkdir()
    ks = KeyStore.create(str(data / KEYSTORE_FILENAME), "")
    ks.set_string("bootstrap.password", "from-keystore")
    ks.save("")
    node = Node(data_path=str(data))
    try:
        assert node.keystore is not None
        import base64
        auth = "Basic " + base64.b64encode(
            b"elastic:from-keystore").decode()
        user = node.security_service.authenticate(
            {"Authorization": auth})
        assert user.username == "elastic"
        st, resp = node.rest_controller.dispatch(
            "POST", "/_nodes/reload_secure_settings", None, {})
        assert st == 200 and resp["_nodes"]["successful"] == 1
    finally:
        node.close()


# ---------------------------------------------------------------------------
# cluster: a node whose keystore disagrees must fail its join
# ---------------------------------------------------------------------------

def _mk_keystore(tmp_path, name, value):
    ks = KeyStore.create(str(tmp_path / f"{name}.keystore"), "")
    ks.set_string("bootstrap.password", value)
    ks.save("")
    return ks


def test_mismatched_keystore_fails_join(tmp_path):
    from elasticsearch_tpu.cluster.node import ClusterNode
    from elasticsearch_tpu.common import keystore as ks_mod
    from elasticsearch_tpu.testing.deterministic import (
        DeterministicTaskQueue,
        DisruptableTransport,
        SimNetwork,
    )
    from elasticsearch_tpu.transport.transport import DiscoveryNode

    ks_mod.secure_setting("bootstrap.password", consistent=True)
    queue = DeterministicTaskQueue(seed=7)
    network = SimNetwork(queue)
    n0 = DiscoveryNode(node_id="dn-0", name="dn0")
    n1 = DiscoveryNode(node_id="dn-1", name="dn1")

    cn0 = ClusterNode(
        DisruptableTransport(n0, network), queue,
        data_path=str(tmp_path / "dn0"),
        seed_nodes=[n0], initial_master_nodes=["dn0"],
        rng=queue.random,
        keystore=_mk_keystore(tmp_path, "a", "shared-secret"))
    cn0.start()
    queue.run_for(60)
    assert cn0.is_master()
    assert (cn0.state.metadata.hashes_of_consistent_settings
            .get("bootstrap.password"))

    # matching keystore joins fine
    cn1 = ClusterNode(
        DisruptableTransport(n1, network), queue,
        data_path=str(tmp_path / "dn1"),
        seed_nodes=[n0], initial_master_nodes=[],
        rng=queue.random,
        keystore=_mk_keystore(tmp_path, "b", "shared-secret"))
    cn1.start()
    queue.run_for(60)
    assert "dn-1" in cn0.state.nodes

    # mismatched keystore: join must be refused
    n2 = DiscoveryNode(node_id="dn-2", name="dn2")
    cn2 = ClusterNode(
        DisruptableTransport(n2, network), queue,
        data_path=str(tmp_path / "dn2"),
        seed_nodes=[n0], initial_master_nodes=[],
        rng=queue.random,
        keystore=_mk_keystore(tmp_path, "c", "WRONG-secret"))
    cn2.start()
    queue.run_for(120)
    assert "dn-2" not in cn0.state.nodes
    for cn in (cn0, cn1, cn2):
        cn.stop()
