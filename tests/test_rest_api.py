"""REST API tests driving the full dispatch path (model: the reference's
YAML rest suites — do/match assertions against the API contract,
rest-api-spec; SURVEY.md §4 tier 5), plus one real-socket smoke test."""

import json

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def test_root_info(node):
    r = do(node, "GET", "/")
    assert r["version"]["distribution"] == "elasticsearch_tpu"


def test_index_crud_lifecycle(node):
    do(node, "PUT", "/books", body={
        "settings": {"index": {"number_of_shards": 2}},
        "mappings": {"properties": {"title": {"type": "text"},
                                    "year": {"type": "long"}}},
    })
    r = do(node, "GET", "/books")
    assert r["books"]["mappings"]["properties"]["title"]["type"] == "text"
    assert r["books"]["settings"]["index"]["number_of_shards"] == 2

    r = do(node, "PUT", "/books/_doc/1", body={"title": "Dune", "year": 1965},
           expect=201)
    assert r["result"] == "created" and r["_version"] == 1
    r = do(node, "PUT", "/books/_doc/1", body={"title": "Dune", "year": 1966})
    assert r["result"] == "updated" and r["_version"] == 2

    r = do(node, "GET", "/books/_doc/1")
    assert r["found"] and r["_source"]["year"] == 1966
    r = do(node, "GET", "/books/_source/1")
    assert r == {"title": "Dune", "year": 1966}

    do(node, "DELETE", "/books/_doc/1")
    do(node, "GET", "/books/_doc/1", expect=404)
    do(node, "DELETE", "/books")
    do(node, "GET", "/books/_doc/1", expect=404)  # index gone -> error body


def test_create_conflict_and_missing_index(node):
    do(node, "PUT", "/idx/_create/1", body={"a": 1}, expect=201)
    r = do(node, "PUT", "/idx/_create/1", body={"a": 2}, expect=409)
    assert r["error"]["type"] == "version_conflict_engine_exception"
    r = do(node, "GET", "/missing/_doc/1", expect=404)
    assert r["error"]["type"] == "index_not_found_exception"


def test_optimistic_concurrency_params(node):
    r = do(node, "PUT", "/idx/_doc/1", body={"v": 1}, expect=201)
    do(node, "PUT", "/idx/_doc/1", body={"v": 2},
       params={"if_seq_no": str(r["_seq_no"]),
               "if_primary_term": str(r["_primary_term"])})
    do(node, "PUT", "/idx/_doc/1", body={"v": 3},
       params={"if_seq_no": str(r["_seq_no"]),
               "if_primary_term": str(r["_primary_term"])}, expect=409)


def test_update_api(node):
    do(node, "PUT", "/idx/_doc/1", body={"a": 1, "nested": {"x": 1}}, expect=201)
    r = do(node, "POST", "/idx/_update/1", body={"doc": {"b": 2, "nested": {"y": 2}}})
    assert r["result"] == "updated"
    src = do(node, "GET", "/idx/_source/1")
    assert src == {"a": 1, "b": 2, "nested": {"x": 1, "y": 2}}
    # noop detection
    r = do(node, "POST", "/idx/_update/1", body={"doc": {"b": 2}})
    assert r["result"] == "noop"
    # upsert
    r = do(node, "POST", "/idx/_update/9", body={"upsert": {"fresh": True},
                                                 "doc": {}}, expect=201)
    assert r["result"] == "created"
    do(node, "POST", "/idx/_update/404", body={"doc": {}}, expect=404)


def test_bulk_ndjson(node):
    ndjson = "\n".join(json.dumps(l) for l in [
        {"index": {"_index": "logs", "_id": "1"}},
        {"msg": "hello", "level": "info"},
        {"index": {"_index": "logs", "_id": "2"}},
        {"msg": "boom", "level": "error"},
        {"create": {"_index": "logs", "_id": "1"}},   # conflict
        {"msg": "dup"},
        {"delete": {"_index": "logs", "_id": "2"}},
    ])
    r = do(node, "POST", "/_bulk", params={"refresh": "true"}, body=ndjson)
    assert r["errors"] is True
    statuses = [list(item.values())[0]["status"] for item in r["items"]]
    assert statuses == [201, 201, 409, 200]
    r = do(node, "GET", "/logs/_search", body={})
    assert r["hits"]["total"]["value"] == 1


def test_search_flow(node):
    for i in range(12):
        do(node, "PUT", f"/articles/_doc/{i}",
           body={"title": f"article about {'jax' if i % 2 else 'numpy'} {i}",
                 "views": i}, expect=201)
    do(node, "POST", "/articles/_refresh")
    r = do(node, "POST", "/articles/_search",
           body={"query": {"match": {"title": "jax"}}, "size": 3})
    assert r["hits"]["total"]["value"] == 6
    assert len(r["hits"]["hits"]) == 3
    assert all("jax" in h["_source"]["title"] for h in r["hits"]["hits"])
    # q= param
    r = do(node, "GET", "/articles/_search", params={"q": "title:numpy"})
    assert r["hits"]["total"]["value"] == 6
    # sort + from/size via params
    r = do(node, "GET", "/articles/_search",
           params={"size": "2", "from": "1"},
           body={"sort": [{"views": "desc"}]})
    assert [h["_source"]["views"] for h in r["hits"]["hits"]] == [10, 9]
    # count
    r = do(node, "GET", "/articles/_count", body={"query": {"match": {"title": "jax"}}})
    assert r["count"] == 6


def test_scroll_over_rest(node):
    for i in range(7):
        do(node, "PUT", f"/s/_doc/{i}", body={"n": i}, expect=201)
    do(node, "POST", "/s/_refresh")
    r = do(node, "POST", "/s/_search", params={"scroll": "1m"},
           body={"size": 3, "sort": [{"n": "asc"}]})
    seen = [h["_source"]["n"] for h in r["hits"]["hits"]]
    sid = r["_scroll_id"]
    while True:
        r = do(node, "POST", "/_search/scroll", body={"scroll_id": sid, "scroll": "1m"})
        if not r["hits"]["hits"]:
            break
        seen.extend(h["_source"]["n"] for h in r["hits"]["hits"])
    assert seen == list(range(7))
    r = do(node, "DELETE", "/_search/scroll", body={"scroll_id": sid})
    assert r["num_freed"] == 1


def test_msearch(node):
    do(node, "PUT", "/a/_doc/1", body={"x": "alpha"}, expect=201)
    do(node, "PUT", "/b/_doc/1", body={"x": "beta"}, expect=201)
    do(node, "POST", "/a/_refresh")
    do(node, "POST", "/b/_refresh")
    nd = "\n".join(json.dumps(l) for l in [
        {"index": "a"}, {"query": {"match_all": {}}},
        {"index": "b"}, {"query": {"match": {"x": "beta"}}},
    ])
    r = do(node, "POST", "/_msearch", body=nd)
    assert len(r["responses"]) == 2
    assert r["responses"][0]["hits"]["total"]["value"] == 1
    assert r["responses"][1]["hits"]["total"]["value"] == 1


def test_mget(node):
    do(node, "PUT", "/m/_doc/1", body={"v": 1}, expect=201)
    do(node, "PUT", "/m/_doc/2", body={"v": 2}, expect=201)
    r = do(node, "POST", "/m/_mget", body={"ids": ["1", "2", "404"]})
    assert [d["found"] for d in r["docs"]] == [True, True, False]


def test_analyze_api(node):
    r = do(node, "POST", "/_analyze",
           body={"analyzer": "standard", "text": "The Quick Fox"})
    assert [t["token"] for t in r["tokens"]] == ["the", "quick", "fox"]


def test_mapping_updates(node):
    do(node, "PUT", "/idx", body={"mappings": {"properties": {"a": {"type": "long"}}}})
    do(node, "PUT", "/idx/_mapping",
       body={"properties": {"b": {"type": "keyword"}}})
    r = do(node, "GET", "/idx/_mapping")
    assert r["idx"]["mappings"]["properties"]["b"]["type"] == "keyword"
    # conflicting change rejected
    r = do(node, "PUT", "/idx/_mapping",
           body={"properties": {"a": {"type": "text"}}}, expect=400)


def test_cluster_and_cat(node):
    do(node, "PUT", "/one/_doc/1", body={"x": 1}, params={"refresh": "true"},
       expect=201)
    r = do(node, "GET", "/_cluster/health")
    assert r["status"] == "green"
    r = do(node, "GET", "/_cat/indices")
    assert "one" in r["_cat"]
    r = do(node, "GET", "/_nodes/stats")
    node_stats = list(r["nodes"].values())[0]
    assert node_stats["indices"]["one"]["docs"]["count"] == 1


def test_rank_eval_endpoint(node):
    for i in range(5):
        do(node, "PUT", f"/r/_doc/{i}", body={"t": "relevant" if i < 2 else "other"},
           expect=201)
    do(node, "POST", "/r/_refresh")
    r = do(node, "POST", "/r/_rank_eval", body={
        "requests": [{"id": "q", "request": {"query": {"match": {"t": "relevant"}}},
                      "ratings": [{"_id": "0", "rating": 1},
                                  {"_id": "1", "rating": 1}]}],
        "metric": {"recall": {"k": 5}},
    })
    assert r["metric_score"] == 1.0


def test_auto_create_on_write(node):
    do(node, "PUT", "/fresh/_doc/1", body={"hello": "world"}, expect=201)
    r = do(node, "GET", "/fresh/_mapping")
    assert r["fresh"]["mappings"]["properties"]["hello"]["type"] == "text"


def test_unknown_route_and_wrong_method(node):
    do(node, "GET", "/_made_up_endpoint_zz", expect=400)
    r = do(node, "DELETE", "/_cluster/health", expect=405)


def test_real_http_socket(node):
    """One end-to-end socket test (the rest drive dispatch directly)."""
    import urllib.request

    port = node.start(port=0)
    base = f"http://127.0.0.1:{port}"

    def req(method, path, body=None, headers=None):
        data = json.dumps(body).encode() if isinstance(body, dict) else body
        r = urllib.request.Request(base + path, data=data, method=method,
                                   headers=headers or {"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(r) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    status, r = req("GET", "/")
    assert status == 200 and "version" in r
    status, r = req("PUT", "/http/_doc/1", {"msg": "over the wire"})
    assert status == 201
    req("POST", "/http/_refresh", b"")
    status, r = req("POST", "/http/_search",
                    {"query": {"match": {"msg": "wire"}}})
    assert status == 200 and r["hits"]["total"]["value"] == 1
    status, r = req("GET", "/missing/_doc/1")
    assert status == 404


def test_bulk_bad_item_does_not_desync(node):
    """A failing index/create item must not shift the action/source framing."""
    ndjson = "\n".join(json.dumps(l) for l in [
        {"index": {"_index": "BadName", "_id": "x"}},   # invalid (uppercase)
        {"f": 1},
        {"index": {"_index": "ok", "_id": "2"}},
        {"f": 2},
    ])
    r = do(node, "POST", "/_bulk", params={"refresh": "true"}, body=ndjson)
    assert r["errors"] is True
    statuses = [list(item.values())[0]["status"] for item in r["items"]]
    assert statuses == [400, 201]
    assert do(node, "GET", "/ok/_doc/2")["_source"] == {"f": 2}


def test_cas_survives_restart(tmp_path):
    from elasticsearch_tpu.common.settings import Settings
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "cas"))
    r = do(n, "PUT", "/c/_doc/1", body={"v": 1}, expect=201)
    r = do(n, "PUT", "/c/_doc/1", body={"v": 2})  # seq_no 1
    do(n, "POST", "/c/_flush")
    n.close()
    n2 = Node(Settings.EMPTY, data_path=str(tmp_path / "cas"))
    g = do(n2, "GET", "/c/_doc/1")
    assert g["_seq_no"] == r["_seq_no"] and g["_version"] == 2
    do(n2, "PUT", "/c/_doc/1", body={"v": 3},
       params={"if_seq_no": str(r["_seq_no"]),
               "if_primary_term": str(r["_primary_term"])})
    n2.close()


def test_msm_string_forms(node):
    for i, t in enumerate(["a b c", "a b", "a"]):
        do(node, "PUT", f"/msm/_doc/{i}", body={"t": t}, expect=201)
    do(node, "POST", "/msm/_refresh")
    r = do(node, "POST", "/msm/_search", body={
        "query": {"match": {"t": {"query": "a b c",
                                  "minimum_should_match": "2"}}}})
    assert r["hits"]["total"]["value"] == 2
    r = do(node, "POST", "/msm/_search", body={
        "query": {"match": {"t": {"query": "a b c",
                                  "minimum_should_match": "67%"}}}})
    assert r["hits"]["total"]["value"] == 2
    # bool-level string msm
    r = do(node, "POST", "/msm/_search", body={
        "query": {"bool": {"should": [
            {"term": {"t": "a"}}, {"term": {"t": "b"}}, {"term": {"t": "c"}}],
            "minimum_should_match": "2"}}})
    assert r["hits"]["total"]["value"] == 2


def test_empty_multi_match_and_dis_max(node):
    do(node, "PUT", "/e/_doc/1", body={"t": "hello"}, params={"refresh": "true"},
       expect=201)
    # multi_match without fields searches all text fields
    r = do(node, "POST", "/e/_search",
           body={"query": {"multi_match": {"query": "hello"}}})
    assert r["hits"]["total"]["value"] == 1
    r = do(node, "POST", "/e/_search",
           body={"query": {"dis_max": {}}}, expect=400)
    assert "dis_max" in r["error"]["reason"]


def test_scroll_reports_total_on_every_page(node):
    for i in range(9):
        do(node, "PUT", f"/sc/_doc/{i}", body={"n": i}, expect=201)
    do(node, "POST", "/sc/_refresh")
    r = do(node, "POST", "/sc/_search", params={"scroll": "1m"},
           body={"size": 4, "sort": [{"n": "asc"}]})
    sid = r["_scroll_id"]
    assert r["hits"]["total"]["value"] == 9
    r = do(node, "POST", "/_search/scroll", body={"scroll_id": sid})
    assert r["hits"]["total"]["value"] == 9  # continuation pages keep total


def test_bulk_json_array_over_http_and_500_handling(tmp_path):
    """A one-line JSON-array _bulk body must work over real HTTP (the
    NDJSON line parser wraps it), and unexpected handler failures must
    answer 500 instead of dropping the connection."""
    import json
    import urllib.error
    import urllib.request

    from elasticsearch_tpu.node import Node
    n = Node(data_path=str(tmp_path / "h"))
    port = n.start(0)
    try:
        data = json.dumps([{"index": {"_index": "t", "_id": "1"}},
                           {"a": 1}]).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/_bulk", data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            r = json.loads(resp.read().decode())
        assert r["errors"] is False
        assert r["items"][0]["index"]["result"] == "created"

        # a handler crash (forced) returns a JSON 500, not a dropped
        # connection
        def boom(node, params, body):
            raise RuntimeError("kaboom")
        n.rest_controller.register("GET", "/_boom", boom)
        req = urllib.request.Request(f"http://127.0.0.1:{port}/_boom")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 500"
        except urllib.error.HTTPError as e:
            assert e.code == 500
            payload = json.loads(e.read().decode())
            assert payload["error"]["type"] == "runtime_error"

        # malformed NDJSON is the CLIENT's fault: 400 parse error
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/_bulk", data=b"not json\n",
            method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        try:
            urllib.request.urlopen(bad)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400

        # pretty-printed JSON-array bodies parse too
        pretty = json.dumps([{"index": {"_index": "t", "_id": "2"}},
                             {"a": 2}], indent=2).encode()
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{port}/_bulk", data=pretty, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2) as resp:
            r = json.loads(resp.read().decode())
        assert r["errors"] is False
    finally:
        n.close()
