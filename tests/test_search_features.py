"""Tests for the search-completeness pack: positional queries, multi-term
queries, more_like_this/pinned/distance_feature, query_string,
rescore/collapse/suggest/explain/profile/script_fields.

Mirrors the reference's per-query-type test style (ref:
AbstractQueryTestCase round-trips every query type; here each type is
executed against a small corpus with hand-checkable results).
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.service import IndicesService
from elasticsearch_tpu.search.service import SearchService


@pytest.fixture()
def svc(tmp_path):
    indices = IndicesService(str(tmp_path))
    return indices, SearchService(indices)


DOCS = [
    {"title": "the quick brown fox", "body": "jumps over the lazy dog",
     "group": "a", "rank": 3},
    {"title": "quick brown rabbits", "body": "rabbits hop quickly away",
     "group": "a", "rank": 1},
    {"title": "brown quick fox", "body": "a fox of a different color",
     "group": "b", "rank": 2},
    {"title": "slow green turtle", "body": "the turtle walks slowly home",
     "group": "b", "rank": 5},
    {"title": "quick silver surfer", "body": "surfing the quick waves",
     "group": "c", "rank": 4},
]


def _index_docs(indices, name="idx", docs=DOCS):
    idx = indices.create_index(name)
    for i, d in enumerate(docs):
        idx.index_doc(str(i), d)
    idx.refresh()
    return idx


def _search(svc, body, index="idx"):
    indices, search = svc
    return search.search(index, body)


def _ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


# ---------------------------------------------------------------- phrase

def test_match_phrase_exact(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_phrase": {"title": "quick brown fox"}}})
    assert _ids(r) == ["0"]  # only doc 0 has the exact sequence


def test_match_phrase_order_matters(svc):
    indices, search = svc
    _index_docs(indices)
    # doc 2 has "brown quick fox" — reversed order must NOT match
    r = _search(svc, {"query": {"match_phrase": {"title": "brown quick fox"}}})
    assert _ids(r) == ["2"]


def test_match_phrase_slop(svc):
    indices, search = svc
    _index_docs(indices)
    # "quick fox": doc 0 is quick [brown] fox — needs slop >= 1
    r0 = _search(svc, {"query": {"match_phrase": {"title": {"query": "quick fox", "slop": 0}}}})
    assert "0" not in _ids(r0)
    r1 = _search(svc, {"query": {"match_phrase": {"title": {"query": "quick fox", "slop": 1}}}})
    assert "0" in _ids(r1)


def test_match_phrase_missing_term_no_match(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_phrase": {"title": "quick zebra"}}})
    assert _ids(r) == []


def test_match_phrase_prefix(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_phrase_prefix": {"title": "quick bro"}}})
    assert set(_ids(r)) == {"0", "1"}


def test_match_bool_prefix(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_bool_prefix": {"title": "fox qui"}}})
    # OR semantics: anything with fox OR qui* matches
    assert "0" in _ids(r) and "4" in _ids(r)


# ------------------------------------------------------------ multi-term

def test_prefix_query(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"prefix": {"title": {"value": "qui"}}}})
    assert set(_ids(r)) == {"0", "1", "2", "4"}


def test_wildcard_query(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"wildcard": {"title": "*row*"}}})
    assert set(_ids(r)) == {"0", "1", "2"}
    r = _search(svc, {"query": {"wildcard": {"title": "f?x"}}})
    assert set(_ids(r)) == {"0", "2"}


def test_regexp_query(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"regexp": {"title": "qu.ck|slow"}}})
    assert set(_ids(r)) == {"0", "1", "2", "3", "4"}


def test_fuzzy_query(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"fuzzy": {"title": {"value": "quikc"}}}})
    assert "0" in _ids(r)  # quikc ~2edits~ quick


def test_fuzzy_exact_term_scores_highest(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"fuzzy": {"title": {"value": "quick"}}}})
    assert len(_ids(r)) >= 4


# ------------------------------------------------- mlt / pinned / df

def test_more_like_this_text(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"more_like_this": {
        "fields": ["title"], "like": "quick brown animals",
        "min_term_freq": 1, "min_doc_freq": 1}}})
    assert "0" in _ids(r) or "1" in _ids(r)


def test_more_like_this_doc_excludes_self(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"more_like_this": {
        "fields": ["title"], "like": [{"_index": "idx", "_id": "0"}],
        "min_term_freq": 1, "min_doc_freq": 1}}})
    assert "0" not in _ids(r)
    assert len(_ids(r)) > 0


def test_pinned_query(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"pinned": {
        "ids": ["3", "4"],
        "organic": {"match": {"title": "quick"}}}}})
    ids = _ids(r)
    assert ids[:2] == ["3", "4"]  # pinned docs first, in order


def test_distance_feature(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"bool": {
        "must": [{"match_all": {}}],
        "should": [{"distance_feature": {
            "field": "rank", "origin": 3, "pivot": 1}}]}}})
    assert _ids(r)[0] == "0"  # rank==3 gets the max boost


# -------------------------------------------------------- query_string

def test_query_string_field_term(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"query_string": {"query": "title:turtle"}}})
    assert _ids(r) == ["3"]


def test_query_string_and_or(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"query_string": {
        "query": "title:quick AND title:fox"}}})
    assert set(_ids(r)) == {"0", "2"}
    r = _search(svc, {"query": {"query_string": {
        "query": "title:turtle OR title:surfer"}}})
    assert set(_ids(r)) == {"3", "4"}


def test_query_string_phrase_and_wildcard(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"query_string": {
        "query": 'title:"quick brown"'}}})
    assert set(_ids(r)) == {"0", "1"}
    r = _search(svc, {"query": {"query_string": {
        "query": "title:tur*"}}})
    assert _ids(r) == ["3"]


def test_simple_query_string(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"simple_query_string": {
        "query": "quick -fox", "fields": ["title"]}}})
    assert "0" not in _ids(r) and "2" not in _ids(r)
    assert "1" in _ids(r)


# ------------------------------------------------------------- rescore

def test_rescore_reorders_top_window(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {
        "query": {"match": {"title": "quick"}},
        "rescore": {"window_size": 10, "query": {
            "rescore_query": {"term": {"group": "c"}},
            "rescore_query_weight": 100.0}}})
    assert _ids(r)[0] == "4"  # group c doc boosted to front


def test_rescore_with_sort_rejected(svc):
    indices, search = svc
    _index_docs(indices)
    from elasticsearch_tpu.common.errors import IllegalArgumentException
    with pytest.raises(IllegalArgumentException):
        _search(svc, {"query": {"match_all": {}},
                      "sort": [{"rank": "asc"}],
                      "rescore": {"query": {"rescore_query": {"match_all": {}}}}})


# ------------------------------------------------------------ collapse

def test_collapse_keeps_best_per_group(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_all": {}},
                      "collapse": {"field": "group"}})
    ids = _ids(r)
    assert len(ids) == 3  # one hit per group a/b/c
    groups = [h["fields"]["group"][0] for h in r["hits"]["hits"]]
    assert sorted(groups) == ["a", "b", "c"]
    # total reflects pre-collapse hits (ES behavior)
    assert r["hits"]["total"]["value"] == 5


# ------------------------------------------------------------- suggest

def test_term_suggester(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"suggest": {
        "my-suggestion": {"text": "quikc", "term": {"field": "title"}}}})
    entries = r["suggest"]["my-suggestion"]
    assert entries[0]["text"] == "quikc"
    options = [o["text"] for o in entries[0]["options"]]
    assert "quick" in options


def test_term_suggester_existing_word_no_options(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"suggest": {
        "s": {"text": "quick", "term": {"field": "title"}}}})
    assert r["suggest"]["s"][0]["options"] == []


def test_phrase_suggester(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"suggest": {
        "s": {"text": "quikc brown", "phrase": {"field": "title"}}}})
    options = [o["text"] for o in r["suggest"]["s"][0]["options"]]
    assert any("quick brown" == o for o in options)


def test_completion_suggester(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"suggest": {
        "s": {"prefix": "qu", "completion": {"field": "title"}}}})
    options = [o["text"] for o in r["suggest"]["s"][0]["options"]]
    assert "quick" in options


# ------------------------------------------- explain / profile / fields

def test_explain_api(svc):
    indices, search = svc
    _index_docs(indices)
    r = search.explain("idx", "0", {"query": {"match": {"title": "quick"}}})
    assert r["matched"] is True
    assert r["explanation"]["value"] > 0
    r = search.explain("idx", "3", {"query": {"match": {"title": "quick"}}})
    assert r["matched"] is False


def test_profile_output(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match": {"title": "quick"}},
                      "profile": True})
    shards = r["profile"]["shards"]
    assert shards and shards[0]["searches"][0]["query"][0]["time_in_nanos"] > 0


def test_script_fields(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_all": {}},
                      "script_fields": {
                          "double_rank": {"script": "doc['rank'].value * 2"}}})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    assert by_id["0"]["fields"]["double_rank"] == [6.0]


def test_fields_api(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_all": {}}, "fields": ["group", "rank"]})
    by_id = {h["_id"]: h for h in r["hits"]["hits"]}
    assert by_id["1"]["fields"]["group"] == ["a"]
    assert by_id["1"]["fields"]["rank"] == [1.0]


def test_terminate_after(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_all": {}}, "terminate_after": 2})
    assert r["terminated_early"] is True
    assert r["hits"]["total"]["value"] == 2


# ------------------------------------------------- segment persistence

def test_token_streams_survive_save_load(tmp_path):
    from elasticsearch_tpu.index.mapper import MapperService
    from elasticsearch_tpu.index.segment import Segment, SegmentWriter

    mapper = MapperService()
    w = SegmentWriter()
    for i, d in enumerate(DOCS):
        w.add(mapper.parse(str(i), d))
    seg = w.build("s0")
    assert "title" in seg.streams
    seg.save(str(tmp_path / "seg"))
    loaded = Segment.load(str(tmp_path / "seg"))
    assert np.array_equal(loaded.streams["title"].tokens,
                          seg.streams["title"].tokens)


def test_token_streams_survive_merge(tmp_path):
    from elasticsearch_tpu.index.mapper import MapperService
    from elasticsearch_tpu.index.segment import SegmentWriter, merge_segments

    mapper = MapperService()
    w1, w2 = SegmentWriter(), SegmentWriter()
    for i, d in enumerate(DOCS[:3]):
        w1.add(mapper.parse(str(i), d))
    for i, d in enumerate(DOCS[3:]):
        w2.add(mapper.parse(str(3 + i), d))
    s1, s2 = w1.build("s1"), w2.build("s2")
    merged = merge_segments("m", [s1, s2])
    ts = merged.streams["title"]
    pf = merged.postings["title"]
    # doc 0's title tokens must decode back to the original sequence
    toks = [pf.terms[t] for t in ts.tokens[0] if t >= 0]
    assert toks == ["the", "quick", "brown", "fox"]
    # deleted docs drop out of streams on merge
    s1.delete(0)
    merged2 = merge_segments("m2", [s1, s2])
    ts2 = merged2.streams["title"]
    first = [merged2.postings["title"].terms[t]
             for t in ts2.tokens[0] if t >= 0]
    assert first == ["quick", "brown", "rabbits"]


# ----------------------------------------------- review regression tests

def test_mlt_in_bool_resolves_across_shards(tmp_path):
    """A more_like_this nested in a bool must resolve its like-doc even
    when the doc lives on a different shard than the one rewriting."""
    from elasticsearch_tpu.search.service import SearchService

    indices = IndicesService(str(tmp_path))
    idx = indices.create_index("multi", {"index.number_of_shards": 4})
    for i, d in enumerate(DOCS):
        idx.index_doc(str(i), d)
    idx.refresh()
    search = SearchService(indices)
    r = search.search("multi", {"query": {"bool": {"must": [
        {"more_like_this": {"fields": ["title"],
                            "like": [{"_index": "multi", "_id": "0"}],
                            "min_term_freq": 1, "min_doc_freq": 1}}]}}})
    ids = [h["_id"] for h in r["hits"]["hits"]]
    assert ids and "0" not in ids


def test_sloppy_phrase_repeated_terms_need_distinct_positions(svc):
    indices, search = svc
    _index_docs(indices, docs=[{"title": "a b"}, {"title": "a a"}])
    r = _search(svc, {"query": {"match_phrase": {
        "title": {"query": "a a", "slop": 1}}}})
    assert _ids(r) == ["1"]  # one 'a' cannot satisfy both slots


def test_phrase_respects_stopword_position_gaps(tmp_path):
    from elasticsearch_tpu.search.service import SearchService

    indices = IndicesService(str(tmp_path))
    idx = indices.create_index("stops", mappings={"properties": {
        "title": {"type": "text", "analyzer": "stop"}}})
    idx.index_doc("0", {"title": "quick the fox"})   # gap between quick, fox
    idx.index_doc("1", {"title": "quick fox"})
    idx.refresh()
    search = SearchService(indices)
    r = search.search("stops", {"query": {"match_phrase": {"title": "quick fox"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["1"]
    r = search.search("stops", {"query": {"match_phrase": {
        "title": {"query": "quick fox", "slop": 1}}}})
    assert {h["_id"] for h in r["hits"]["hits"]} == {"0", "1"}


def test_match_phrase_prefix_slop(svc):
    indices, search = svc
    _index_docs(indices)
    # doc 0: "the quick brown fox" — "quick fo*" needs slop 1 (brown gap)
    r0 = _search(svc, {"query": {"match_phrase_prefix": {
        "title": {"query": "quick fo", "slop": 0}}}})
    assert "0" not in _ids(r0)
    r1 = _search(svc, {"query": {"match_phrase_prefix": {
        "title": {"query": "quick fo", "slop": 1}}}})
    assert "0" in _ids(r1)


def test_profile_with_empty_query_object(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {}, "profile": True})  # must not crash
    assert r["profile"]["shards"]


def test_terminate_after_consistent_response(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"match_all": {}}, "terminate_after": 2})
    assert r["terminated_early"] is True
    assert len(r["hits"]["hits"]) <= r["hits"]["total"]["value"]


def test_rewrite_does_not_mutate_query_tree(svc):
    from elasticsearch_tpu.search.queries import parse_query

    indices, search = svc
    idx = _index_docs(indices)
    q = parse_query({"bool": {"must": [{"more_like_this": {
        "fields": ["title"], "like": [{"_index": "idx", "_id": "0"}],
        "min_term_freq": 1, "min_doc_freq": 1}}]}})
    searcher = idx.shard_searchers()[0]
    q2 = q.rewrite(searcher)
    assert q2 is not q
    from elasticsearch_tpu.search.queries import MoreLikeThisQuery
    assert isinstance(q.must[0], MoreLikeThisQuery)  # original untouched


def test_mlt_inside_function_score_rewrites(svc):
    indices, search = svc
    _index_docs(indices)
    r = _search(svc, {"query": {"function_score": {
        "query": {"more_like_this": {"fields": ["title"],
                                     "like": "quick brown fox",
                                     "min_term_freq": 1, "min_doc_freq": 1}},
        "functions": [{"weight": 2.0}]}}})
    assert _ids(r)


def test_malformed_single_field_specs_raise_parsing_exception():
    from elasticsearch_tpu.common.errors import ParsingException
    from elasticsearch_tpu.search.queries import parse_query

    for qtype in ("match_phrase", "match_phrase_prefix", "match_bool_prefix",
                  "prefix", "wildcard", "regexp", "fuzzy"):
        with pytest.raises(ParsingException):
            parse_query({qtype: {"a": "x", "b": "y"}})
        with pytest.raises(ParsingException):
            parse_query({qtype: {"boost": 2.0}})


def _kw_sort_index(tmp_path_factory, shards=1):
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("kwsort")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index(
        "k", {"index.number_of_shards": shards},
        {"properties": {"name": {"type": "keyword"},
                        "n": {"type": "long"}}})
    return indices, idx, SearchService(indices)


def test_keyword_sort_scroll_across_segments(tmp_path_factory):
    # multi-segment shard: scroll with keyword sort must not lose docs
    # (segment-local ordinals are not comparable across segments)
    indices, idx, svc = _kw_sort_index(tmp_path_factory)
    idx.index_doc("1", {"name": "a", "n": 1})
    idx.index_doc("2", {"name": "b", "n": 2})
    idx.refresh()                      # segment 0: {a, b}
    idx.index_doc("3", {"name": "c", "n": 3})
    idx.refresh()                      # segment 1: {c}
    r = svc.search("k", {"sort": [{"name": "asc"}], "size": 1},
                   scroll="1m")
    got = [h["_source"]["name"] for h in r["hits"]["hits"]]
    sid = r["_scroll_id"]
    while True:
        r = svc.scroll(sid)
        if not r["hits"]["hits"]:
            break
        got += [h["_source"]["name"] for h in r["hits"]["hits"]]
    assert got == ["a", "b", "c"]
    indices.close()


def test_keyword_search_after(tmp_path_factory):
    indices, idx, svc = _kw_sort_index(tmp_path_factory, shards=2)
    for i, nm in enumerate(["delta", "alpha", "echo", "bravo", "charlie"]):
        idx.index_doc(str(i), {"name": nm, "n": i})
    idx.refresh()
    r = svc.search("k", {"sort": [{"name": "asc"}], "size": 2})
    names = [h["_source"]["name"] for h in r["hits"]["hits"]]
    assert names == ["alpha", "bravo"]
    after = r["hits"]["hits"][-1]["sort"]
    r = svc.search("k", {"sort": [{"name": "asc"}], "size": 10,
                         "search_after": after})
    names2 = [h["_source"]["name"] for h in r["hits"]["hits"]]
    assert names2 == ["charlie", "delta", "echo"]
    indices.close()


def test_dfs_query_then_fetch_consistent_idf(tmp_path_factory):
    """Without DFS, shards score with local IDF; dfs_query_then_fetch
    must produce identical scores for identical docs on different shards
    (ref: search/dfs/DfsPhase cross-shard-consistent IDF)."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("dfs")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index(
        "d", {"index.number_of_shards": 2},
        {"properties": {"t": {"type": "text"}}})
    # identical docs that land on different shards, plus skewed term
    # frequencies so per-shard IDF differs
    docs = {"a": "quake alpha", "b": "quake alpha",
            "k0": "quake beta", "k1": "quake beta", "k2": "quake gamma"}
    for did, text in docs.items():
        idx.index_doc(did, {"t": text})
    idx.refresh()
    svc = SearchService(indices)
    r = svc.search("d", {"query": {"match": {"t": {"query": "quake"}}},
                         "size": 10},
                   search_type="dfs_query_then_fetch")
    scores = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
    # every doc contains "quake" once with similar lengths — with global
    # IDF the identical docs a and b MUST score identically
    assert scores["a"] == scores["b"]
    indices.close()


def _hybrid_index(tmp_path_factory):
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("hybrid")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("h", {}, {"properties": {
        "t": {"type": "text"},
        "v": {"type": "dense_vector", "dims": 4}}})
    docs = {
        "text-hit": {"t": "quantum computing hardware", "v": [0, 0, 0, 1.0]},
        "vec-hit": {"t": "gardening tips", "v": [1.0, 0, 0, 0]},
        "both-hit": {"t": "quantum computing", "v": [0.9, 0.1, 0, 0]},
        "neither": {"t": "cooking pasta", "v": [0, 1.0, 0, 0]},
    }
    for did, d in docs.items():
        idx.index_doc(did, d)
    idx.refresh()
    return indices, SearchService(indices)


def test_top_level_knn_merges_with_query(tmp_path_factory):
    indices, svc = _hybrid_index(tmp_path_factory)
    r = svc.search("h", {
        "query": {"match": {"t": {"query": "quantum"}}},
        "knn": {"field": "v", "query_vector": [1.0, 0, 0, 0]},
        "size": 4})
    ids = [h["_id"] for h in r["hits"]["hits"]]
    # both-hit scores from BOTH branches → first
    assert ids[0] == "both-hit"
    assert set(ids) >= {"both-hit", "vec-hit", "text-hit"}
    indices.close()


def test_rrf_hybrid_fusion(tmp_path_factory):
    indices, svc = _hybrid_index(tmp_path_factory)
    r = svc.search("h", {
        "query": {"match": {"t": {"query": "quantum"}}},
        "knn": {"field": "v", "query_vector": [1.0, 0, 0, 0]},
        "rank": {"rrf": {"rank_constant": 60, "window_size": 10}},
        "size": 4})
    hits = r["hits"]["hits"]
    assert [h["_id"] for h in hits][0] == "both-hit"  # in both branches
    # RRF score of the winner = sum over branches of 1/(60+rank)
    assert hits[0]["_score"] > hits[1]["_score"]
    assert hits[0]["_score"] == pytest.approx(1 / 61 + 1 / 62, rel=1e-6)
    indices.close()


def test_top_level_knn_k_limits_matches(tmp_path_factory):
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("knnk")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("k", {}, {"properties": {
        "v": {"type": "dense_vector", "dims": 2}}})
    import math
    for i in range(20):
        a = i * math.pi / 40
        idx.index_doc(str(i), {"v": [math.cos(a), math.sin(a)]})
    idx.refresh()
    svc = SearchService(indices)
    r = svc.search("k", {"knn": {"field": "v", "query_vector": [1.0, 0.0],
                                 "k": 3},
                         "size": 20, "track_total_hits": True})
    # only the 3 nearest vectors match, not all 20
    assert r["hits"]["total"]["value"] == 3
    assert [h["_id"] for h in r["hits"]["hits"]] == ["0", "1", "2"]
    # rrf + scroll is rejected
    import pytest as _pytest
    from elasticsearch_tpu.common.errors import IllegalArgumentException
    with _pytest.raises(IllegalArgumentException):
        svc.search("k", {"knn": {"field": "v", "query_vector": [1, 0]},
                         "rank": {"rrf": {}}}, scroll="1m")
    indices.close()


def test_sliced_scroll_partitions_disjoint_and_complete(tmp_path_factory):
    """slice {id, max} with scroll: every doc lands in exactly one slice
    (ref: search/slice/SliceBuilder — the deep-scan parallelism model)."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("slice")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("s", {"index.number_of_shards": 2},
                               {"properties": {"n": {"type": "long"}}})
    for i in range(40):
        idx.index_doc(str(i), {"n": i})
    idx.refresh()
    svc = SearchService(indices)
    seen = []
    for sid in range(3):
        r = svc.search("s", {"slice": {"id": sid, "max": 3},
                             "size": 10}, scroll="1m")
        ids_slice = [h["_id"] for h in r["hits"]["hits"]]
        scroll_id = r["_scroll_id"]
        while True:
            r = svc.scroll(scroll_id)
            if not r["hits"]["hits"]:
                break
            ids_slice += [h["_id"] for h in r["hits"]["hits"]]
        assert ids_slice            # every slice gets some docs
        seen.extend(ids_slice)
    assert sorted(seen, key=int) == [str(i) for i in range(40)]
    assert len(seen) == len(set(seen))      # disjoint
    indices.close()


def test_text_expansion_query(tmp_path_factory):
    """Learned-sparse scoring over rank_features columns (the brief's
    text_expansion surface): score = sum of query-weight x doc-weight."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    tmp = tmp_path_factory.mktemp("sparse")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("s", {}, {"properties": {
        "expansion": {"type": "rank_features"}}})
    idx.index_doc("1", {"expansion": {"quantum": 2.0, "physics": 1.0}})
    idx.index_doc("2", {"expansion": {"cooking": 3.0, "physics": 0.5}})
    idx.index_doc("3", {"expansion": {"gardening": 1.0}})
    idx.refresh()
    svc = SearchService(indices)
    r = svc.search("s", {"query": {"text_expansion": {"expansion": {
        "tokens": {"quantum": 1.5, "physics": 1.0}}}}})
    hits = {h["_id"]: h["_score"] for h in r["hits"]["hits"]}
    assert set(hits) == {"1", "2"}
    assert hits["1"] == pytest.approx(1.5 * 2.0 + 1.0 * 1.0)
    assert hits["2"] == pytest.approx(1.0 * 0.5)
    # weighted_tokens list form
    r = svc.search("s", {"query": {"weighted_tokens": {"expansion": {
        "tokens": [{"token": "gardening", "weight": 2.0}]}}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["3"]
    indices.close()


def test_text_expansion_boost_and_errors(tmp_path_factory):
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    from elasticsearch_tpu.common.errors import ParsingException
    tmp = tmp_path_factory.mktemp("sparse2")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("s2", {}, {"properties": {
        "e": {"type": "rank_features"}}})
    idx.index_doc("1", {"e": {"x": 2.0}})
    idx.refresh()
    svc = SearchService(indices)
    r1 = svc.search("s2", {"query": {"text_expansion": {"e": {
        "tokens": {"x": 1.0}}}}})
    r2 = svc.search("s2", {"query": {"text_expansion": {"e": {
        "tokens": {"x": 1.0}}, "boost": 3.0}}})
    assert r2["hits"]["hits"][0]["_score"] == pytest.approx(
        3.0 * r1["hits"]["hits"][0]["_score"])
    for bad in ({"text_expansion": {}},
                {"text_expansion": {"e": "nope"}},
                {"text_expansion": {"e": {"tokens": {}}}},
                {"text_expansion": {"e": {"tokens": [{"nope": 1}]}}},
                {"text_expansion": {"e": {"tokens": {"x": "NaNope"}}}}):
        with pytest.raises(ParsingException):
            svc.search("s2", {"query": bad})
    indices.close()


def test_rrf_knn_branch_batched_parity(tmp_path_factory):
    """The batched kNN-branch path (KnnBatcher →
    ops.vector.knn_nominate_batch) returns the SAME fusion as the dense
    per-request path; it engages when the response needs only ids+scores
    from the branch (_source false)."""
    indices, svc = _hybrid_index(tmp_path_factory)
    body = {
        "query": {"match": {"t": {"query": "quantum"}}},
        "knn": {"field": "v", "query_vector": [1.0, 0, 0, 0]},
        "rank": {"rrf": {"rank_constant": 60, "window_size": 10}},
        "size": 4}
    dense = svc.search("h", dict(body))
    launches0 = svc.knn_batcher.launches
    batched = svc.search("h", {**body, "_source": False})
    assert svc.knn_batcher.launches > launches0   # batched path engaged
    assert ([h["_id"] for h in batched["hits"]["hits"]]
            == [h["_id"] for h in dense["hits"]["hits"]])
    assert ([h["_score"] for h in batched["hits"]["hits"]]
            == pytest.approx([h["_score"]
                              for h in dense["hits"]["hits"]]))
    indices.close()


def test_knn_batcher_concurrent_requests_share_launches(tmp_path_factory):
    """Concurrent hybrid requests coalesce: far fewer kNN launches than
    requests (the continuous-batching contract)."""
    import threading as _t
    indices, svc = _hybrid_index(tmp_path_factory)
    base = {
        "knn": {"field": "v", "query_vector": [0.0, 1.0, 0, 0]},
        "rank": {"rrf": {}}, "query": {"match": {"t": "quantum"}},
        "size": 3, "_source": False}
    svc.search("h", dict(base))          # warm compile
    # on CPU launches are sub-ms so leaders never wait (fast devices
    # don't batch); force the measured-latency window so the cohort
    # protocol is actually exercised like on a slow transport
    svc.knn_batcher._lat_ema = 1.0
    launches0 = svc.knn_batcher.launches
    n_req, errs, results = 24, [], []
    lock = _t.Lock()

    def one(i):
        b = {**base, "knn": {**base["knn"],
                             "query_vector": [0.1 * (i % 3), 1.0, 0, 0]}}
        try:
            r = svc.search("h", b)
            with lock:
                results.append(r)
        except Exception as e:            # pragma: no cover
            with lock:
                errs.append(e)

    threads = [_t.Thread(target=one, args=(i,)) for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(results) == n_req
    added = svc.knn_batcher.launches - launches0
    # coalescing is timing-dependent on a fast device (the window only
    # engages while other work is pending); require no loss and no
    # over-launching — the parity test pins correctness
    assert 1 <= added <= n_req
    assert svc.knn_batcher.batched_queries >= n_req
    indices.close()


def test_pure_knn_batched_parity(tmp_path_factory):
    """A pure top-level knn body with `_source: false` (BASELINE
    config 4's serving shape) rides the batched cohort kernel and
    returns the same ids/ordering and total semantics as the dense
    merged-query path."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    import math
    tmp = tmp_path_factory.mktemp("pknn")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("k", {}, {"properties": {
        "v": {"type": "dense_vector", "dims": 2}}})
    for i in range(20):
        a = i * math.pi / 40
        idx.index_doc(str(i), {"v": [math.cos(a), math.sin(a)]})
    idx.refresh()
    svc = SearchService(indices)
    body = {"knn": {"field": "v", "query_vector": [1.0, 0.0], "k": 3},
            "size": 20}
    dense = svc.search("k", dict(body))
    launches0 = svc.knn_batcher.launches
    batched = svc.search("k", {**body, "_source": False})
    assert svc.knn_batcher.launches > launches0
    assert ([h["_id"] for h in batched["hits"]["hits"]]
            == [h["_id"] for h in dense["hits"]["hits"]] == ["0", "1", "2"])
    # total = the k nearest match, exactly like the dense path
    assert batched["hits"]["total"]["value"] == 3
    assert batched["hits"]["total"]["relation"] == "eq"
    # scores follow the knn transform parity
    assert batched["hits"]["hits"][0]["_score"] == pytest.approx(
        dense["hits"]["hits"][0]["_score"], rel=1e-5)
    # richer bodies (wanting _source) still take the dense path
    launches1 = svc.knn_batcher.launches
    r = svc.search("k", dict(body))
    assert svc.knn_batcher.launches == launches1
    assert r["hits"]["hits"][0].get("_source") is not None
    indices.close()


def test_pure_knn_batched_respects_deletes_and_big_cuts(tmp_path_factory):
    """Deleted docs never surface through the batched kNN path (the
    device live mask rides the kernel), and cuts beyond the bucket
    table fall back to the dense path instead of truncating."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    import math
    tmp = tmp_path_factory.mktemp("dknn")
    indices = IndicesService(str(tmp / "data"))
    idx = indices.create_index("k", {}, {"properties": {
        "v": {"type": "dense_vector", "dims": 2}}})
    for i in range(10):
        a = i * math.pi / 20
        idx.index_doc(str(i), {"v": [math.cos(a), math.sin(a)]})
    idx.refresh()
    svc = SearchService(indices)
    body = {"knn": {"field": "v", "query_vector": [1.0, 0.0], "k": 5},
            "size": 10, "_source": False}
    r = svc.search("k", dict(body))
    assert [h["_id"] for h in r["hits"]["hits"]][0] == "0"
    # delete the nearest doc; the batched path must not return it
    idx.delete_doc("0")
    idx.refresh()
    launches0 = svc.knn_batcher.launches
    r = svc.search("k", dict(body))
    assert svc.knn_batcher.launches > launches0
    ids = [h["_id"] for h in r["hits"]["hits"]]
    assert "0" not in ids
    assert ids[0] == "1"
    assert r["hits"]["total"]["value"] == 5
    # window beyond the bucket table: dense fallback, still correct
    launches1 = svc.knn_batcher.launches
    r = svc.search("k", {"knn": {"field": "v", "query_vector": [1.0, 0],
                                 "k": 5000},
                         "size": 5000, "_source": False})
    assert svc.knn_batcher.launches == launches1   # dense path served
    assert len(r["hits"]["hits"]) == 9
    assert "0" not in [h["_id"] for h in r["hits"]["hits"]]
    # version flag disables the shortcut (response shape parity)
    launches2 = svc.knn_batcher.launches
    r = svc.search("k", {**body, "version": True})
    assert svc.knn_batcher.launches == launches2
    assert r["hits"]["hits"][0].get("_version") is not None
    indices.close()
