"""Cluster-wide task management: parent/child task trees over
transport, live `_tasks` fan-out, and cross-node cancellation that
reaches the engine's per-shard / device-launch loops.

The contract under test (ref: TaskManager + TransportListTasksAction /
TransportCancelTasksAction semantics):

- every cluster search/bulk registers a cancellable coordinator parent;
  per-shard handlers on data nodes register children under the remote
  parent carried in the ``task.id`` request header;
- ``list_tasks(group_by=parents)`` on a live multi-node search shows the
  coordinator parent with per-shard children attributed to their owning
  nodes, all cross-linked to one ``trace.id``;
- cancelling the parent from ANY node stops in-flight shard work on
  OTHER nodes: unresolved shards fold into the partial-results protocol
  as typed ``task_cancelled_exception`` failures, and the ban table
  kills children that register AFTER the cancel (the race the ban
  design exists for);
- seed replay yields identical task trees.

Chaos scenarios are @pytest.mark.chaos(seed=N); a red run echoes its
seed and replays with ``pytest <nodeid> --chaos-seed=N``.
"""

import pytest
from test_search_failover import ChaosCluster, _setup

from elasticsearch_tpu.cluster.search_action import (
    QUERY_PHASE_ACTION,
    SEARCH_ACTION,
    TASK_CANCELLED_TYPE,
)
from elasticsearch_tpu.testing.deterministic import DISCONNECTED
from elasticsearch_tpu.testing.faults import DELAY, FaultRule
from elasticsearch_tpu.transport.tasks import (
    EMPTY_TASK_ID,
    TaskId,
    TaskManager,
    build_tasks_response,
    filter_task_dicts,
    render_cat_tasks,
)

# ---------------------------------------------------------------------------
# TaskManager unit contract: bans, counters, shaping
# ---------------------------------------------------------------------------


def test_ban_kills_child_registered_after_cancel():
    tm = TaskManager("n1")
    parent = tm.register("transport", "indices:data/read/search",
                         cancellable=True)
    tm.cancel(parent, "test")
    child = tm.register("transport", QUERY_PHASE_ACTION,
                        parent_task_id=TaskId("n1", parent.id),
                        cancellable=True)
    assert child.is_cancelled()
    assert "parent banned" in child.cancellation_reason()
    tm.unregister(child)
    tm.unregister(parent)
    # the ban dies with the parent: a later child is NOT cancelled
    late = tm.register("transport", QUERY_PHASE_ACTION,
                       parent_task_id=TaskId("n1", parent.id),
                       cancellable=True)
    assert not late.is_cancelled()
    tm.unregister(late)


def test_remote_ban_cancels_registered_children_and_future_ones():
    """set_ban(cancel_children=True) is the remote half of a cancel:
    already-registered children die AND later arrivals die on
    registration."""
    tm = TaskManager("data-1")
    remote_parent = TaskId("coord-1", 7)
    child = tm.register("transport", QUERY_PHASE_ACTION,
                        parent_task_id=remote_parent, cancellable=True)
    tm.set_ban(remote_parent, "by user request", cancel_children=True)
    assert child.is_cancelled()
    late = tm.register("transport", QUERY_PHASE_ACTION,
                       parent_task_id=remote_parent, cancellable=True)
    assert late.is_cancelled()
    tm.remove_ban(remote_parent)
    ok = tm.register("transport", QUERY_PHASE_ACTION,
                     parent_task_id=remote_parent, cancellable=True)
    assert not ok.is_cancelled()
    for t in (child, late, ok):
        tm.unregister(t)
    assert tm.stats()["cancelled"] == 2
    assert tm.stats()["current"] == 0


def test_task_manager_stats_and_peak():
    tm = TaskManager("n1")
    a = tm.register("transport", "a")
    b = tm.register("transport", "b", cancellable=True)
    assert tm.stats()["current"] == 2
    assert tm.stats()["peak_concurrent"] == 2
    tm.cancel(b, "x")
    tm.cancel(b, "x")     # idempotent: counted once
    tm.unregister(a)
    tm.unregister(b)
    s = tm.stats()
    assert s == {"current": 0, "peak_concurrent": 2, "started": 2,
                 "completed": 2, "cancelled": 1, "bans": 0}


def test_tasks_response_shaping_group_by():
    infos = {
        "n1": {"name": "node1", "tasks": [
            {"node": "n1", "id": 1, "type": "transport",
             "action": SEARCH_ACTION, "description": "d",
             "start_time_in_millis": 1, "running_time_in_nanos": 5,
             "cancellable": True}]},
        "n2": {"name": "node2", "tasks": [
            {"node": "n2", "id": 3, "type": "transport",
             "action": QUERY_PHASE_ACTION, "description": "d2",
             "start_time_in_millis": 2, "running_time_in_nanos": 4,
             "cancellable": True, "parent_task_id": "n1:1"}]},
    }
    by_nodes = build_tasks_response(infos, group_by="nodes")
    assert by_nodes["nodes"]["n1"]["tasks"]["n1:1"]["action"] == \
        SEARCH_ACTION
    flat = build_tasks_response(infos, group_by="none")
    assert set(flat["tasks"]) == {"n1:1", "n2:3"}
    tree = build_tasks_response(infos, group_by="parents")
    assert set(tree["tasks"]) == {"n1:1"}
    (child,) = tree["tasks"]["n1:1"]["children"]
    assert child["node"] == "n2" and child["id"] == 3
    with pytest.raises(Exception):
        build_tasks_response(infos, group_by="bogus")
    # filters
    only_search = filter_task_dicts(
        [t for i in infos.values() for t in i["tasks"]],
        actions="indices:data/read/search")
    assert len(only_search) == 1
    stripped = filter_task_dicts(infos["n1"]["tasks"], detailed=False)
    assert "description" not in stripped[0]
    cat = render_cat_tasks(infos)
    assert "indices:data/read/search n1:1 -" in cat
    assert "n1:1 transport" in cat.splitlines()[1]


# ---------------------------------------------------------------------------
# cluster harness helpers
# ---------------------------------------------------------------------------


def _slow_queries(cluster, step_delay=0.3):
    """Make every data node's per-shard query loop yield between shards,
    so cancels/bans/`_tasks` RPCs interleave mid-search."""
    for cn in cluster.cluster_nodes.values():
        cn.search_service.query_step_delay = step_delay


def _start_search(cluster, coord, body=None):
    box = {}

    def on_done(result, err=None):
        box["result"] = result
        box["err"] = err

    coord.search("logs", body or {"query": {"match": {"body": "fox"}},
                                  "size": 5}, on_done=on_done)
    return box


def _call_fast(cluster, fn, *args, timeout=10.0, **kwargs):
    """cluster.call with fine-grained sim steps (0.05s instead of 1s),
    so mid-flight probes — list/get/cancel — resolve while the slowed
    search is still running."""
    box = {}

    def on_done(result, err=None):
        box["result"] = result
        box["err"] = err

    fn(*args, **kwargs, on_done=on_done)
    waited = 0.0
    while "result" not in box and "err" not in box and waited < timeout:
        cluster.run_for(0.05)
        waited += 0.05
    assert "result" in box or "err" in box, "call never completed"
    if box.get("err") is not None:
        raise box["err"]
    return box["result"]


def _await(cluster, box, timeout=60):
    waited = 0.0
    while "result" not in box and "err" not in box and waited < timeout:
        cluster.run_for(1.0)
        waited += 1.0
    assert "result" in box or "err" in box, "search never completed"
    if box.get("err") is not None:
        raise box["err"]
    return box["result"]


# ---------------------------------------------------------------------------
# live `_tasks` fan-out
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=31)
def test_live_search_shows_parent_child_tree(tmp_path, chaos_seed):
    """`list_tasks(group_by=parents)` mid-search: one coordinator parent
    (`indices:data/read/search`) with per-shard query children
    attributed to their owning nodes, all sharing the parent's
    trace.id."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=4, replicas=0)
    _slow_queries(cluster)
    coord = cluster.master()
    box = _start_search(cluster, coord)
    cluster.run_for(0.2)    # queries delivered, children registered

    tree = _call_fast(cluster, coord.list_tasks,
                      {"group_by": "parents", "detailed": True})
    roots = {tid: t for tid, t in tree["tasks"].items()
             if t["action"] == SEARCH_ACTION}
    assert len(roots) == 1, f"seed={chaos_seed}: {tree}"
    (root_id, root), = roots.items()
    assert root["node"] == coord.local_node.node_id
    assert root["cancellable"] is True
    assert "source[" in root["description"]
    children = root.get("children", [])
    assert children, f"seed={chaos_seed}: no live children in {tree}"
    assert {c["action"] for c in children} == {QUERY_PHASE_ACTION}
    assert all(c["parent_task_id"] == root_id for c in children)
    # children live on their owning nodes, not (only) the coordinator
    child_nodes = {c["node"] for c in children}
    assert child_nodes <= set(cluster.cluster_nodes)
    # one trace cross-links the whole tree (`_tasks` ↔ `_traces`)
    trace_ids = {root["trace.id"]} | {c["trace.id"] for c in children}
    assert len(trace_ids) == 1 and None not in trace_ids, \
        f"seed={chaos_seed}: {trace_ids}"

    # cluster-aware GET /_tasks/{id} from a NON-owner node resolves the
    # owner itself
    other = cluster.coordinator_excluding(coord.local_node.node_id)
    got = _call_fast(cluster, other.get_task, root_id)
    assert got["completed"] is False
    assert got["task"]["action"] == SEARCH_ACTION

    resp = _await(cluster, box)
    assert resp["_shards"]["failed"] == 0
    # everything unregistered once the search finished
    done = cluster.call(coord.list_tasks, {"group_by": "none"})
    assert not any(t["action"].startswith("indices:data/read/search")
                   for t in done["tasks"].values()), done
    with pytest.raises(Exception):
        _call_fast(cluster, other.get_task, root_id)   # finished → 404


@pytest.mark.chaos(seed=32)
def test_bulk_registers_parent_and_shard_children(tmp_path, chaos_seed):
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=2, replicas=1)
    coord = cluster.master()
    started = {nid: cn.task_manager.stats()["started"]
               for nid, cn in cluster.cluster_nodes.items()}
    resp = cluster.call(coord.bulk, "logs",
                        [{"op": "index", "id": f"t-{i}",
                          "source": {"body": "task tree", "n": i}}
                         for i in range(8)])
    assert resp["errors"] == []
    # the coordinator registered the bulk parent...
    m = coord.telemetry.metrics
    assert m.get_value("tasks.started",
                       action="indices:data/write/bulk") >= 1
    # ...and at least one node registered primary shard-bulk children
    # + replica grandchildren under it
    assert any(
        cn.telemetry.metrics.get_value(
            "tasks.started",
            action="indices:data/write/bulk[s][p]") >= 1
        for cn in cluster.cluster_nodes.values())
    assert any(
        cn.telemetry.metrics.get_value(
            "tasks.started",
            action="indices:data/write/bulk[s][r]") >= 1
        for cn in cluster.cluster_nodes.values())
    # all task work completed (started == completed cluster-wide)
    for nid, cn in cluster.cluster_nodes.items():
        s = cn.task_manager.stats()
        assert s["current"] == 0, f"seed={chaos_seed}: {nid}: {s}"
    assert sum(cn.task_manager.stats()["started"] - started[nid]
               for nid, cn in cluster.cluster_nodes.items()) >= 3


# ---------------------------------------------------------------------------
# cancellation that bites
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=33)
def test_cancel_mid_query_stops_remote_shards_partial_results(
        tmp_path, chaos_seed):
    """POST /_tasks/{id}/_cancel against the coordinator parent while
    shard queries run on OTHER nodes: the data-node children report
    cancelled (their remaining shards never execute) and the search
    returns partial results with typed task_cancelled failures."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=6, replicas=0, n=30)
    _slow_queries(cluster, step_delay=0.5)
    coord = cluster.master()
    box = _start_search(cluster, coord)
    cluster.run_for(0.2)

    parents = coord.task_manager.list_tasks(actions=SEARCH_ACTION)
    assert len(parents) == 1, f"seed={chaos_seed}"
    parent_id = f"{coord.local_node.node_id}:{parents[0].id}"

    # cancel from a DIFFERENT node: it must resolve the owner itself
    other = cluster.coordinator_excluding(coord.local_node.node_id)
    cancel_resp = _call_fast(cluster, other.cancel_task, parent_id)
    cancelled_task = list(
        cancel_resp["nodes"][coord.local_node.node_id]["tasks"]
        .values())[0]
    assert cancelled_task["cancelled"] is True

    resp = _await(cluster, box)
    failures = resp["_shards"].get("failures", [])
    cancelled_failures = [f for f in failures
                          if f["reason"]["type"] == TASK_CANCELLED_TYPE]
    assert cancelled_failures, f"seed={chaos_seed}: {resp['_shards']}"
    assert resp["_shards"]["failed"] >= len(cancelled_failures)
    # a data-node child on ANOTHER node observed the cancellation (via
    # the ban broadcast), not just the coordinator's own shards
    remote_cancelled = [
        nid for nid, cn in cluster.cluster_nodes.items()
        if nid != coord.local_node.node_id
        and cn.task_manager.stats()["cancelled"] >= 1]
    assert remote_cancelled, f"seed={chaos_seed}: cancel never reached " \
        "a remote data node"
    cluster.run_for(10)
    for nid, cn in cluster.cluster_nodes.items():
        s = cn.task_manager.stats()
        assert s["current"] == 0, f"seed={chaos_seed}: {nid}: {s}"
        # the ban markers were swept once the cancelled parent finished
        assert s["bans"] == 0, f"seed={chaos_seed}: {nid}: {s}"


@pytest.mark.chaos(seed=34)
def test_cancel_before_child_registers_ban_kills_on_arrival(
        tmp_path, chaos_seed):
    """The ban-table race: the query RPC to one node is delayed past the
    cancel, so its child does not exist when the ban arrives — yet it
    still dies (cancelled at registration) and answers typed
    task_cancelled errors."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=4, replicas=0)
    coord = cluster.master()
    # every query RPC arrives ~2s late; the cancel lands well before
    cluster.injector.add_rule(FaultRule(
        action=QUERY_PHASE_ACTION, mode=DELAY, delay=(2.0, 2.0)))
    box = _start_search(cluster, coord)
    cluster.run_for(0.2)
    parents = coord.task_manager.list_tasks(actions=SEARCH_ACTION)
    assert len(parents) == 1, f"seed={chaos_seed}"
    parent_id = f"{coord.local_node.node_id}:{parents[0].id}"
    cluster.call(coord.cancel_task, parent_id)

    resp = _await(cluster, box)
    # the parent resolved every group as cancelled — all shards failed,
    # yet the partial-results protocol returns a response, not an error
    assert resp["_shards"]["failed"] == resp["_shards"]["total"]
    assert all(f["reason"]["type"] == TASK_CANCELLED_TYPE
               for f in resp["_shards"]["failures"])
    # drive the delayed queries to arrival: children register against
    # the ban and die without running a single shard
    cluster.run_for(10)
    born_dead = [nid for nid, cn in cluster.cluster_nodes.items()
                 if cn.task_manager.stats()["cancelled"] >= 1]
    assert born_dead, f"seed={chaos_seed}: ban never killed a child"
    for cn in cluster.cluster_nodes.values():
        assert cn.task_manager.stats()["current"] == 0


@pytest.mark.chaos(seed=38)
def test_cancel_between_query_and_fetch_reports_typed_failures(
        tmp_path, chaos_seed):
    """A cancel landing AFTER the query phase reduced but BEFORE the
    fetch fan-out must not look like a clean zero-hit result: the
    skipped shards become typed task_cancelled failures (phase=fetch)
    while the reduced totals survive."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=4, replicas=0)
    coord = cluster.master()
    svc = coord.search_service
    orig_fetch = svc._fetch_phase

    def cancel_then_fetch(ctx):
        coord.task_manager.cancel(ctx["task"], "between phases")
        orig_fetch(ctx)

    svc._fetch_phase = cancel_then_fetch
    try:
        box = _start_search(cluster, coord)
        resp = _await(cluster, box)
    finally:
        svc._fetch_phase = orig_fetch
    assert resp["hits"]["hits"] == []
    assert resp["hits"]["total"]["value"] > 0   # reduced totals kept
    shards = resp["_shards"]
    assert shards["failed"] == shards["total"], shards
    assert all(f["reason"]["type"] == TASK_CANCELLED_TYPE
               and f["reason"]["phase"] == "fetch"
               for f in shards["failures"]), shards
    cluster.run_for(5)
    for cn in cluster.cluster_nodes.values():
        assert cn.task_manager.stats()["current"] == 0


@pytest.mark.chaos(seed=35)
def test_seed_replay_yields_identical_task_trees(tmp_path, chaos_seed):
    """Two runs from one seed observe the SAME mid-flight task tree
    (ids, actions, parents, owning nodes) — tasks ride the same
    deterministic schedule as everything else."""

    def one_run(subdir):
        cluster = ChaosCluster(3, tmp_path / subdir, seed=chaos_seed)
        _setup(cluster, shards=4, replicas=0)
        _slow_queries(cluster)
        coord = cluster.master()
        box = _start_search(cluster, coord)
        cluster.run_for(0.2)
        flat = _call_fast(cluster, coord.list_tasks, {"group_by": "none"})
        _await(cluster, box)
        return sorted(
            (tid, t["action"], t.get("parent_task_id", ""), t["node"],
             t.get("trace.id", ""))
            for tid, t in flat["tasks"].items()
            if t["action"].startswith("indices:data/read/search"))

    assert one_run("a") == one_run("b"), f"seed={chaos_seed}"


# ---------------------------------------------------------------------------
# fan-out resilience + cat surface
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=36)
def test_list_tasks_reports_unreachable_node_as_failure(
        tmp_path, chaos_seed):
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    cluster.stabilise()
    coord = cluster.master()
    dead = next(n for n in cluster.nodes
                if n.node_id != coord.local_node.node_id)
    cluster.network.isolate(dead, cluster.nodes, mode=DISCONNECTED)
    resp = cluster.call(coord.list_tasks, {})
    assert dead.node_id not in resp["nodes"]
    assert any(f["node_id"] == dead.node_id
               for f in resp.get("node_failures", []))
    cluster.network.heal()


@pytest.mark.chaos(seed=37)
def test_cat_tasks_renders_cluster_rows(tmp_path, chaos_seed):
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, shards=4, replicas=0)
    _slow_queries(cluster)
    coord = cluster.master()
    box = _start_search(cluster, coord)
    cluster.run_for(0.2)
    text = _call_fast(cluster, coord.cat_tasks)
    assert SEARCH_ACTION in text, f"seed={chaos_seed}: {text!r}"
    _await(cluster, box)


# ---------------------------------------------------------------------------
# cluster-state publication lag detector (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=38)
def test_missed_publication_repairs_via_resend(tmp_path, chaos_seed):
    """A node partitioned through one publication misses it but stays a
    member; the next follower check carries the leader's applied
    version, the laggard requests a resend, and it catches up WITHOUT
    any further state change (the PR-4 known issue)."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = cluster.stabilise()
    lagger = next(n for n in cluster.nodes
                  if n.node_id != master.local_node.node_id)
    lag_cn = cluster.cluster_nodes[lagger.node_id]
    cluster.network.isolate(lagger, cluster.nodes, mode=DISCONNECTED)
    resp = cluster.call(master.create_index, "lagidx",
                        number_of_shards=1, number_of_replicas=0,
                        timeout=2)
    assert resp == {"acknowledged": True}
    assert lag_cn.state.version < master.state.version, \
        f"seed={chaos_seed}: laggard applied the state it missed?"
    # the master's view shows the lag (stale follower-check record)
    assert master.cluster_state_stats()["state_lag"][lagger.node_id] \
        >= 1
    cluster.network.heal()
    cluster.run_for(15)
    assert lag_cn.state.version == master.state.version, \
        f"seed={chaos_seed}: resend never repaired the laggard"
    assert "lagidx" in lag_cn.state.metadata.indices
    assert master.cluster_state_stats()["state_lag"][lagger.node_id] \
        == 0
    assert lag_cn.cluster_state_stats()["version"] == \
        master.state.version


@pytest.mark.chaos(seed=39)
def test_pending_cluster_tasks_shape(tmp_path, chaos_seed):
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = cluster.stabilise()
    # quiesced master: empty queue; entries carry the pending shape
    assert master.pending_cluster_tasks() == []
    master.coordinator.submit_state_update("noop-probe", lambda s: s)
    # non-master nodes report their own (empty) queue
    other = cluster.coordinator_excluding(master.local_node.node_id)
    assert other.pending_cluster_tasks() == []
    cluster.run_for(5)
