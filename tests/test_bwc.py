"""Backwards-compatibility harness (VERDICT r2 item 8; ref:
qa/full-cluster-restart/ + qa/rolling-upgrade/):

- a CHECKED-IN data dir written by the v1 on-disk format
  (tests/fixtures/bwc_v1.tar.gz, frozen by make_bwc_fixture.py) must
  boot on the current build: segments load, the translog tail replays,
  deletes stay deleted, aliases/templates/stored scripts survive, and
  the index serves reads AND writes afterwards;
- a segment written by a NEWER format generation is refused with a
  clear error (the downgrade guard);
- a mixed-wire-version cluster forms and serves (the rolling-upgrade
  handshake contract: compatibility is a RANGE, not equality).
"""

import json
import os
import tarfile

import pytest

from elasticsearch_tpu.node import Node

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(HERE, "fixtures", "bwc_v1.tar.gz")
MANIFEST = os.path.join(HERE, "fixtures", "bwc_v1.json")


def call(node, method, path, body=None, expect=(200, 201), **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    assert status in expect, (status, r)
    return r


@pytest.fixture()
def old_data(tmp_path):
    with tarfile.open(FIXTURE) as tar:
        tar.extractall(tmp_path, filter="data")
    return str(tmp_path / "data")


def test_v1_data_dir_boots_and_serves(old_data):
    with open(MANIFEST) as fh:
        manifest = json.load(fh)
    node = Node(data_path=old_data)
    try:
        # committed docs load from the old segments
        for did, title in manifest["docs"].items():
            if did == "6":
                continue
            doc = call(node, "GET", f"/library/_doc/{did}")
            assert doc["found"] and doc["_source"]["title"] == title
        # the translog tail replays ops never flushed by the old build
        doc = call(node, "GET", "/library/_doc/6")
        assert doc["_source"]["title"] == manifest["docs"]["6"]
        # deletes stay deleted
        for did in manifest["deleted"]:
            call(node, "GET", f"/library/_doc/{did}", expect=(404,))
        # search across old segments + replayed tail
        call(node, "POST", "/library/_refresh")
        r = call(node, "POST", "/library/_search",
                 {"query": {"match": {"title": "quick"}}})
        assert {h["_id"] for h in r["hits"]["hits"]} == {"1", "3"}
        # keyword + numeric doc values survived
        r = call(node, "POST", "/library/_search", {
            "size": 0, "query": {"match_all": {}},
            "aggs": {"g": {"terms": {"field": "genre"}},
                     "y": {"max": {"field": "year"}}}})
        buckets = {b["key"]: b["doc_count"]
                   for b in r["aggregations"]["g"]["buckets"]}
        assert buckets == {"fable": 3, "drama": 1, "nature": 1}
        assert r["aggregations"]["y"]["value"] == 2024
        # alias, stored script, index template survived
        r = call(node, "POST", f"/{manifest['alias']}/_search",
                 {"query": {"match_all": {}}})
        assert r["hits"]["total"]["value"] == 5
        assert call(node, "GET", "/_scripts/bwc-boost")["found"]
        # the old index accepts NEW writes on the new build
        call(node, "PUT", "/library/_doc/7",
             {"title": "written by the new build", "year": 2026,
              "genre": "nature"})
        call(node, "POST", "/library/_refresh")
        r = call(node, "POST", "/library/_search",
                 {"query": {"match": {"title": "build"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["7"]
    finally:
        node.close()


def test_v1_data_survives_flush_and_second_restart(old_data):
    """Write → flush on the new build, then restart AGAIN: the upgraded
    store must stay loadable (the full-cluster-restart double-bounce)."""
    node = Node(data_path=old_data)
    call(node, "PUT", "/library/_doc/8",
         {"title": "second generation doc", "year": 2026,
          "genre": "drama"})
    call(node, "POST", "/library/_flush")
    node.close()

    node2 = Node(data_path=old_data)
    try:
        assert call(node2, "GET", "/library/_doc/8")["found"]
        assert call(node2, "GET", "/library/_doc/1")["found"]
        call(node2, "POST", "/library/_refresh")
        r = call(node2, "POST", "/library/_search",
                 {"query": {"match": {"title": "generation"}}})
        assert [h["_id"] for h in r["hits"]["hits"]] == ["8"]
    finally:
        node2.close()


def test_newer_segment_format_refused(tmp_path, old_data):
    """A future format generation must fail loudly, not corrupt."""
    node = Node(data_path=old_data)
    idx_path = node.indices_service.get("library").path
    node.close()
    seg_dirs = []
    for root, dirs, files in os.walk(idx_path):
        if "meta.json" in files:
            seg_dirs.append(root)
    assert seg_dirs
    meta_path = os.path.join(seg_dirs[0], "meta.json")
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["format_version"] = 99
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    from elasticsearch_tpu.index.segment import Segment
    with pytest.raises(IOError, match="NEWER build"):
        Segment.load(seg_dirs[0])


def test_mixed_wire_version_cluster_forms():
    """A peer one wire version AHEAD still handshakes (rolling upgrade:
    compatibility is a range down to MIN_COMPATIBLE_VERSION); a peer
    BELOW the minimum is refused."""
    from elasticsearch_tpu.transport import transport as tmod
    from elasticsearch_tpu.transport.transport import (
        HANDSHAKE_ACTION, DiscoveryNode, TcpTransport, TransportService)

    def mk(name):
        t = TcpTransport(DiscoveryNode(node_id=name, name=name,
                                       host="127.0.0.1", port=0))
        return TransportService(t)

    old, new = mk("v1-node"), mk("v2-node")
    try:
        # the "new" node advertises CURRENT+1 (a mid-rolling-upgrade
        # mix) — swap its handshake handler in place
        from elasticsearch_tpu.transport.transport import RequestHandler
        new.transport._handlers[HANDSHAKE_ACTION] = RequestHandler(
            HANDSHAKE_ACTION,
            lambda req, channel, src: channel.send_response(
                {"version": tmod.CURRENT_VERSION + 1,
                 "node": new.transport.local_node.to_dict()}),
            "generic")
        old.connect_to_node(new.transport.local_node)

        # a peer BELOW the minimum compatible version is rejected
        too_old = mk("v0-node")
        try:
            too_old.transport._handlers[HANDSHAKE_ACTION] = \
                RequestHandler(
                    HANDSHAKE_ACTION,
                    lambda req, channel, src: channel.send_response(
                        {"version": tmod.MIN_COMPATIBLE_VERSION - 1,
                         "node":
                         too_old.transport.local_node.to_dict()}),
                    "generic")
            from elasticsearch_tpu.transport.transport import (
                ConnectTransportException)
            with pytest.raises(ConnectTransportException,
                               match="incompatible"):
                old.connect_to_node(too_old.transport.local_node)
        finally:
            too_old.close()
    finally:
        old.close()
        new.close()


@pytest.mark.chaos(seed=41)
def test_join_below_min_compatible_refused_typed(tmp_path, chaos_seed):
    """The join barrier refuses a wire version the fleet cannot talk
    to, with the typed coordination error (not a generic reject)."""
    from elasticsearch_tpu.cluster.coordination import (
        IncompatibleVersionException)
    from elasticsearch_tpu.testing.deterministic import (
        DisruptableTransport)
    from elasticsearch_tpu.transport.transport import DiscoveryNode
    from test_cluster_node import SimDataCluster

    c = SimDataCluster(2, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    ghost = DiscoveryNode(node_id="dn-ancient", name="dn-ancient",
                          host="127.0.0.1", port=0)
    # the ghost handshakes at wire version 0 — below the floor the
    # fleet can ever talk to
    ancient = DisruptableTransport(ghost, c.network)
    ancient.wire_version = 0
    with pytest.raises(IncompatibleVersionException,
                       match="below the minimum compatible"):
        m.coordinator._validate_joiner_version(ghost, None)


@pytest.mark.chaos(seed=43)
def test_v1_rejoin_of_upgraded_cluster_refused(tmp_path, chaos_seed):
    """Once every member speaks v2 the published min_wire_version is 2
    and a v1 node is a DOWNGRADE: its rejoin is refused and the cluster
    stays at the surviving members."""
    from elasticsearch_tpu.cluster.coordination import (
        IncompatibleVersionException)
    from test_cluster_node import SimDataCluster

    c = SimDataCluster(3, tmp_path, seed=chaos_seed)
    m = c.stabilise()
    assert m.state.metadata.min_wire_version == 2
    vid = next(n.node_id for n in c.nodes
               if n.node_id != m.local_node.node_id)
    c.call(m.put_node_shutdown, vid, "restart", allocation_delay="60s")
    c.stop_node(vid)
    c.run_for(20)
    # the bounced node comes back DOWNGRADED to wire v1
    c.restart_node(vid, wire_version=1)
    c.run_for(60)
    m = c.master()
    assert m.state.nodes.size == 2, \
        "a v1 node must not rejoin a v2-upgraded cluster"
    assert vid not in {n.node_id for n in m.state.nodes.nodes}
    # and the barrier refuses it with the typed error
    joiner = next(n for n in c.nodes if n.node_id == vid)
    with pytest.raises(IncompatibleVersionException,
                       match="downgrades are not supported"):
        m.coordinator._validate_joiner_version(joiner, 1)
