"""Analysis chain tests (model: the reference's analysis-common tests +
ESTokenStreamTestCase assertions)."""

import pytest

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.analysis.filters import PorterStemFilter
from elasticsearch_tpu.analysis.tokenizers import StandardTokenizer, Token
from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.settings import Settings


def test_standard_analyzer():
    reg = AnalysisRegistry()
    terms = reg.get("standard").terms("The Quick-Brown Fox, jumped over 2 dogs!")
    assert terms == ["the", "quick", "brown", "fox", "jumped", "over", "2", "dogs"]


def test_standard_tokenizer_offsets_positions():
    toks = StandardTokenizer().tokenize("foo bar")
    assert toks == [Token("foo", 0, 0, 3), Token("bar", 1, 4, 7)]


def test_whitespace_and_keyword():
    reg = AnalysisRegistry()
    assert reg.get("whitespace").terms("Foo Bar-Baz") == ["Foo", "Bar-Baz"]
    assert reg.get("keyword").terms("New York") == ["New York"]


def test_stop_analyzer():
    reg = AnalysisRegistry()
    assert reg.get("stop").terms("the quick fox") == ["quick", "fox"]


def test_english_analyzer_stems():
    reg = AnalysisRegistry()
    assert reg.get("english").terms("running quickly") == ["run", "quickli"]


@pytest.mark.parametrize("word,stem", [
    ("caresses", "caress"), ("ponies", "poni"), ("cats", "cat"),
    ("feed", "feed"), ("agreed", "agre"), ("plastered", "plaster"),
    ("motoring", "motor"), ("sing", "sing"), ("conflated", "conflat"),
    ("troubled", "troubl"), ("sized", "size"), ("hopping", "hop"),
    ("falling", "fall"), ("hissing", "hiss"), ("happy", "happi"),
    ("relational", "relat"), ("conditional", "condit"),
    ("vietnamization", "vietnam"), ("predication", "predic"),
    ("feudalism", "feudal"), ("hopefulness", "hope"),
    ("formalize", "formal"), ("electricity", "electr"),
    ("adjustable", "adjust"), ("defensible", "defens"),
    ("effective", "effect"), ("probate", "probat"), ("rate", "rate"),
    ("controlling", "control"), ("rolling", "roll"),
])
def test_porter_stemmer_vectors(word, stem):
    # classic vectors from Porter's 1980 paper
    f = PorterStemFilter()
    assert f._stem(word) == stem


def test_unicode_folding():
    reg = AnalysisRegistry(Settings.from_dict({
        "index.analysis.analyzer.folded.type": "custom",
        "index.analysis.analyzer.folded.tokenizer": "standard",
        "index.analysis.analyzer.folded.filter": ["lowercase", "asciifolding"],
    }))
    assert reg.get("folded").terms("Crème Brûlée") == ["creme", "brulee"]


def test_custom_analyzer_from_settings():
    reg = AnalysisRegistry(Settings.from_dict({
        "index.analysis.filter.my_stop.type": "stop",
        "index.analysis.filter.my_stop.stopwords": ["foo"],
        "index.analysis.analyzer.my.type": "custom",
        "index.analysis.analyzer.my.tokenizer": "whitespace",
        "index.analysis.analyzer.my.filter": ["lowercase", "my_stop"],
    }))
    assert reg.get("my").terms("Foo BAR baz") == ["bar", "baz"]


def test_html_strip_char_filter():
    reg = AnalysisRegistry(Settings.from_dict({
        "index.analysis.analyzer.h.type": "custom",
        "index.analysis.analyzer.h.tokenizer": "standard",
        "index.analysis.analyzer.h.char_filter": ["html_strip"],
        "index.analysis.analyzer.h.filter": ["lowercase"],
    }))
    assert reg.get("h").terms("<p>Hello &amp; <b>World</b></p>") == ["hello", "world"]


def test_unknown_analyzer_raises():
    reg = AnalysisRegistry()
    with pytest.raises(IllegalArgumentException):
        reg.get("nope")


def test_unknown_filter_raises():
    with pytest.raises(IllegalArgumentException):
        AnalysisRegistry(Settings.from_dict({
            "index.analysis.analyzer.bad.type": "custom",
            "index.analysis.analyzer.bad.tokenizer": "standard",
            "index.analysis.analyzer.bad.filter": ["made_up"],
        }))


def test_shingle_filter():
    reg = AnalysisRegistry(Settings.from_dict({
        "index.analysis.analyzer.sh.type": "custom",
        "index.analysis.analyzer.sh.tokenizer": "whitespace",
        "index.analysis.analyzer.sh.filter": ["shingle"],
    }))
    assert reg.get("sh").terms("a b c") == ["a", "a b", "b", "b c", "c"]


def test_ngram_tokenizer():
    reg = AnalysisRegistry(Settings.from_dict({
        "index.analysis.tokenizer.ng.type": "ngram",
        "index.analysis.tokenizer.ng.min_gram": 2,
        "index.analysis.tokenizer.ng.max_gram": 3,
        "index.analysis.analyzer.ng.type": "custom",
        "index.analysis.analyzer.ng.tokenizer": "ng",
    }))
    assert reg.get("ng").terms("abcd") == ["ab", "abc", "bc", "bcd", "cd"]


def test_synonym_filter():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "filter": {"syn": {"type": "synonym",
                           "synonyms": ["car, auto", "tv => television"]}},
        "analyzer": {"a": {"type": "custom", "tokenizer": "standard",
                           "filter": ["lowercase", "syn"]}}}}}))
    terms = [t.term for t in r.get("a").analyze("my car and tv")]
    assert terms == ["my", "car", "auto", "and", "television"]
    # synonyms share the original token's position (phrase semantics)
    toks = r.get("a").analyze("car")
    assert {t.position for t in toks} == {0}


def test_phonetic_filters():
    from elasticsearch_tpu.analysis.filters import metaphone, soundex
    assert soundex("smith") == soundex("smyth")
    assert soundex("robert") == "R163"
    assert metaphone("catherine") == metaphone("kathryn")


def test_word_delimiter_graph():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "analyzer": {"a": {"type": "custom", "tokenizer": "whitespace",
                           "filter": ["word_delimiter_graph",
                                      "lowercase"]}}}}}))
    terms = [t.term for t in r.get("a").analyze("PowerShot500 foo-bar")]
    assert terms == ["power", "shot", "500", "foo", "bar"]


def test_cjk_bigram():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "analyzer": {"a": {"type": "custom", "tokenizer": "standard",
                           "filter": ["cjk_bigram"]}}}}}))
    terms = [t.term for t in r.get("a").analyze("日本語 test")]
    assert terms == ["日本", "本語", "test"]


def test_elision_and_apostrophe():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "analyzer": {
            "fr": {"type": "custom", "tokenizer": "whitespace",
                   "filter": ["lowercase", "elision"]},
            "tr": {"type": "custom", "tokenizer": "whitespace",
                   "filter": ["apostrophe"]}}}}}))
    assert [t.term for t in r.get("fr").analyze("l'avion")] == ["avion"]
    assert [t.term for t in r.get("tr").analyze("Istanbul'da")] == [
        "Istanbul"]


def test_keyword_marker_protects_stemming():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "filter": {"km": {"type": "keyword_marker",
                          "keywords": ["running"]}},
        "analyzer": {"a": {"type": "custom", "tokenizer": "standard",
                           "filter": ["lowercase", "km",
                                      "porter_stem"]}}}}}))
    terms = [t.term for t in r.get("a").analyze("running jumping")]
    assert terms == ["running", "jump"]


def test_word_delimiter_unicode_and_positions():
    from elasticsearch_tpu.analysis.filters import WordDelimiterGraphFilter
    from elasticsearch_tpu.analysis.tokenizers import Token
    f = WordDelimiterGraphFilter()
    toks = f.filter([Token("café-bar", 0, 0, 8)])
    assert [t.term for t in toks] == ["café", "bar"]
    toks = f.filter([Token("PowerShot", 0, 0, 9)])
    assert [(t.term, t.position) for t in toks] == [
        ("Power", 0), ("Shot", 1)]
    toks = f.filter([Token("XMLHttp", 0, 0, 7)])
    assert [t.term for t in toks] == ["XML", "Http"]


def test_keyword_marker_survives_rebuilding_filters():
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
    from elasticsearch_tpu.common.settings import Settings
    r = AnalysisRegistry(Settings.from_dict({"index": {"analysis": {
        "filter": {"km": {"type": "keyword_marker",
                          "keywords": ["running"]}},
        "analyzer": {"a": {"type": "custom", "tokenizer": "whitespace",
                           "filter": ["km", "lowercase", "asciifolding",
                                      "porter_stem"]}}}}}))
    terms = [t.term for t in r.get("a").analyze("running jumping")]
    assert terms == ["running", "jump"]


def test_cjk_bigram_preserves_noncjk_positions():
    from elasticsearch_tpu.analysis.filters import CjkBigramFilter
    from elasticsearch_tpu.analysis.tokenizers import Token
    f = CjkBigramFilter()
    # stop-word gap at position 2 must survive
    toks = f.filter([Token("alpha", 0, 0, 5), Token("gamma", 2, 10, 15)])
    assert [(t.term, t.position) for t in toks] == [
        ("alpha", 0), ("gamma", 2)]


def test_analyze_explain_detail(tmp_path):
    """_analyze explain:true returns per-stage detail (ref:
    TransportAnalyzeAction DetailAnalyzeResponse)."""
    from elasticsearch_tpu.node import Node

    node = Node(data_path=str(tmp_path / "ax"))
    try:
        st, r = node.rest_controller.dispatch(
            "GET", "/_analyze", None,
            {"tokenizer": "standard",
             "char_filter": ["html_strip"],
             "filter": ["lowercase", "porter_stem"],
             "text": "<b>Running</b> QUICKLY", "explain": True})
        assert st == 200, r
        d = r["detail"]
        assert d["custom_analyzer"] is True
        assert d["charfilters"][0]["name"] == "html_strip"
        assert "<b>" not in d["charfilters"][0]["filtered_text"][0]
        tok_terms = [t["token"] for t in d["tokenizer"]["tokens"]]
        assert tok_terms == ["Running", "QUICKLY"]
        stages = {tf["name"]: [t["token"] for t in tf["tokens"]]
                  for tf in d["tokenfilters"]}
        assert stages["lowercase"] == ["running", "quickly"]
        assert stages[list(stages)[-1]][0] == "run"   # stemmed last stage
    finally:
        node.close()
