"""Plugin SPI tests (ref: PluginsServiceTests + the per-plugin smoke
tests like AnalysisPhoneticPlugin's): directory discovery, registry
contribution for every extension point, REST usage of a plugin query,
and the shipped analysis-phonetic proof plugin.

Registries are module-global (one engine per process), so negative
assertions defensively clear the keys they probe."""

import json
import os
import textwrap

import pytest

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.plugins import PluginsService, main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_demo_plugin(pdir):
    os.makedirs(pdir, exist_ok=True)
    with open(os.path.join(pdir, "plugin.json"), "w") as f:
        json.dump({"name": "demo", "module": "demo_plugin",
                   "class": "ESPlugin"}, f)
    with open(os.path.join(pdir, "demo_plugin.py"), "w") as f:
        f.write(textwrap.dedent("""
            from elasticsearch_tpu.plugins import Plugin
            from elasticsearch_tpu.search.queries import (MatchAllQuery,
                                                          _with_boost)

            def _parse_everything(spec):
                return _with_boost(MatchAllQuery(), spec or {})

            def _shout(cfg, svc):
                field = cfg.get("field", "msg")
                def run(doc):
                    if field in doc.source:
                        doc.source[field] = str(doc.source[field]).upper()
                    return doc
                return run

            def _doc_parity(body, sub, ctx, mapper):
                import numpy as np
                even = 0
                for seg, mask, *_ in ctx:
                    even += int(np.sum(np.nonzero(mask)[0] % 2 == 0))
                return {"value": even}

            class ESPlugin(Plugin):
                name = "demo"
                def queries(self):
                    return {"everything": _parse_everything}
                def ingest_processors(self):
                    return {"shout": _shout}
                def aggregations(self):
                    return {"even_docs": _doc_parity}
                def rest_handlers(self):
                    return [("GET", "/_demo/ping",
                             lambda node, params, body:
                             (200, {"pong": True}))]
        """))


def test_dir_plugin_all_extension_points(tmp_path):
    pdir = tmp_path / "plugins" / "demo"
    write_demo_plugin(str(pdir))
    node = Node(settings=Settings.from_dict(
        {"path": {"plugins": str(tmp_path / "plugins")}}),
        data_path=str(tmp_path / "data"))
    try:
        assert [p["name"] for p in node.plugins_service.info()] == ["demo"]
        st, resp = node.rest_controller.dispatch("GET", "/_cat/plugins",
                                                 None, None)
        assert st == 200 and "demo" in resp["_cat"]
        # plugin REST route
        st, resp = node.rest_controller.dispatch("GET", "/_demo/ping",
                                                 None, None)
        assert (st, resp) == (200, {"pong": True})

        node.rest_controller.dispatch("PUT", "/t", None, None)
        # plugin ingest processor
        node.rest_controller.dispatch(
            "PUT", "/_ingest/pipeline/p1", None,
            {"processors": [{"shout": {"field": "msg"}}]})
        node.rest_controller.dispatch(
            "PUT", "/t/_doc/1", {"pipeline": "p1"}, {"msg": "quiet"})
        node.rest_controller.dispatch("POST", "/t/_refresh", None, None)
        st, resp = node.rest_controller.dispatch(
            "GET", "/t/_doc/1", None, None)
        assert resp["_source"]["msg"] == "QUIET"

        # plugin query type over REST
        st, resp = node.rest_controller.dispatch(
            "POST", "/t/_search", None, {"query": {"everything": {}}})
        assert st == 200 and resp["hits"]["total"]["value"] == 1

        # plugin aggregation
        st, resp = node.rest_controller.dispatch(
            "POST", "/t/_search", None,
            {"size": 0, "query": {"match_all": {}},
             "aggs": {"e": {"even_docs": {}}}})
        assert st == 200 and resp["aggregations"]["e"]["value"] == 1
    finally:
        node.close()


def test_phonetic_requires_plugin(tmp_path):
    from elasticsearch_tpu.analysis import analyzers as an
    an._TOKEN_FILTERS.pop("phonetic", None)   # defensive vs other tests

    node = Node(data_path=str(tmp_path / "bare"))
    try:
        st, resp = node.rest_controller.dispatch(
            "PUT", "/p", None,
            {"settings": {"analysis": {
                "analyzer": {"ph": {"type": "custom",
                                    "tokenizer": "standard",
                                    "filter": ["phonetic"]}}}},
             "mappings": {"properties": {
                 "name": {"type": "text", "analyzer": "ph"}}}})
        # unknown filter must fail index creation or analysis use
        if st == 200:
            st2, _ = node.rest_controller.dispatch(
                "GET", "/p/_analyze", None,
                {"analyzer": "ph", "text": "smith"})
            assert st2 >= 400
    finally:
        node.close()


def test_analysis_phonetic_proof_plugin(tmp_path):
    plugins_dir = str(tmp_path / "plugins")
    rc = plugin_cli(["install",
                     os.path.join(REPO_ROOT, "plugins_src",
                                  "analysis_phonetic"),
                     "--plugins-dir", plugins_dir])
    assert rc == 0
    node = Node(settings=Settings.from_dict(
        {"path": {"plugins": plugins_dir}}),
        data_path=str(tmp_path / "data"))
    try:
        assert any(p["name"] == "analysis-phonetic"
                   for p in node.plugins_service.info())
        st, _ = node.rest_controller.dispatch(
            "PUT", "/p", None,
            {"settings": {"analysis": {
                "filter": {"sx": {"type": "phonetic",
                                  "encoder": "soundex"}},
                "analyzer": {"ph": {"type": "custom",
                                    "tokenizer": "standard",
                                    "filter": ["lowercase", "sx"]}}}},
             "mappings": {"properties": {
                 "name": {"type": "text", "analyzer": "ph"}}}})
        assert st == 200
        for i, nm in enumerate(["smith", "smyth", "jones"]):
            node.rest_controller.dispatch("PUT", f"/p/_doc/{i}", None,
                                          {"name": nm})
        node.rest_controller.dispatch("POST", "/p/_refresh", None, None)
        # phonetic match: smith finds smyth too
        st, resp = node.rest_controller.dispatch(
            "POST", "/p/_search", None,
            {"query": {"match": {"name": "smith"}}})
        assert st == 200
        ids = {h["_id"] for h in resp["hits"]["hits"]}
        assert ids == {"0", "1"}
    finally:
        node.close()


def test_plugin_cli_roundtrip(tmp_path):
    plugins_dir = str(tmp_path / "pd")
    src = str(tmp_path / "src")
    write_demo_plugin(src)
    assert plugin_cli(["install", src, "--plugins-dir", plugins_dir]) == 0
    with pytest.raises(SystemExit):
        plugin_cli(["install", src, "--plugins-dir", plugins_dir])
    assert plugin_cli(["remove", "demo", "--plugins-dir", plugins_dir]) == 0


def test_repository_type_plugin(tmp_path):
    pdir = tmp_path / "plugins" / "repoplug"
    os.makedirs(pdir, exist_ok=True)
    with open(pdir / "plugin.json", "w") as f:
        json.dump({"name": "repoplug", "module": "repo_plugin",
                   "class": "ESPlugin"}, f)
    with open(pdir / "repo_plugin.py", "w") as f:
        f.write(textwrap.dedent("""
            import os
            from elasticsearch_tpu.plugins import Plugin
            from elasticsearch_tpu.repositories.blobstore import (
                BlobStoreRepository)

            class ESPlugin(Plugin):
                name = "repoplug"
                def repository_types(self):
                    # a fake cloud backend: same blobstore contract over
                    # a fixture directory (the zero-egress test strategy)
                    def make(name, config, data_path):
                        base = config.get("settings", {}).get("bucket",
                                                              name)
                        loc = os.path.join(data_path or ".",
                                           "fake-cloud", base)
                        return BlobStoreRepository(name, loc)
                    return {"fake_s3": make}
        """))
    node = Node(settings=Settings.from_dict(
        {"path": {"plugins": str(tmp_path / "plugins")}}),
        data_path=str(tmp_path / "data"))
    try:
        st, _ = node.rest_controller.dispatch(
            "PUT", "/_snapshot/cloudy", None,
            {"type": "fake_s3", "settings": {"bucket": "b1"}})
        assert st == 200
        node.rest_controller.dispatch("PUT", "/s", None, None)
        node.rest_controller.dispatch("PUT", "/s/_doc/1", None,
                                      {"x": 1})
        node.rest_controller.dispatch("POST", "/s/_refresh", None, None)
        st, resp = node.rest_controller.dispatch(
            "PUT", "/_snapshot/cloudy/snap1",
            {"wait_for_completion": "true"}, {"indices": "s"})
        assert st == 200, resp
    finally:
        node.close()
