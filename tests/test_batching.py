"""Continuous-batching tests: concurrent plan-path searches coalesce into
shared launches and return exactly what solo execution returns."""

import threading

import numpy as np
import pytest

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.search.batching import PlanBatcher
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.search.queries import parse_query
from elasticsearch_tpu.search.searcher import ShardSearcher

MAPPINGS = {"properties": {"title": {"type": "text"},
                           "tag": {"type": "keyword"}}}
VOCAB = ["ant", "bee", "cat", "dog", "elk", "fox", "gnu", "hen", "ibis",
         "jay"]


@pytest.fixture(scope="module")
def searcher():
    rng = np.random.default_rng(3)
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i in range(200):
        doc = {"title": " ".join(rng.choice(VOCAB, rng.integers(2, 9))),
               "tag": str(rng.choice(["a", "b"]))}
        w.add(svc.parse(str(i), doc))
    seg = w.build("b0")
    return ShardSearcher([seg], svc, DeviceSegmentCache())


def q(text):
    return parse_query({"match": {"title": text}})


def test_batched_equals_solo(searcher):
    queries = [" ".join(pair) for pair in
               [("ant", "bee"), ("cat", "dog"), ("elk", "fox"),
                ("gnu", "hen"), ("ibis", "jay"), ("ant", "fox")]]
    solo = []
    searcher.batcher = None
    for text in queries:
        r = searcher.query_phase(q(text), 20)
        solo.append(([(d.segment_idx, d.docid, round(d.score, 4))
                      for d in r.docs], r.total_hits))

    searcher.batcher = PlanBatcher()
    results = [None] * len(queries)
    errs = []

    def run(i):
        try:
            r = searcher.query_phase(q(queries[i]), 20)
            results[i] = ([(d.segment_idx, d.docid, round(d.score, 4))
                           for d in r.docs], r.total_hits)
        except BaseException as exc:  # noqa: BLE001
            errs.append(exc)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(queries))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert results == solo
    searcher.batcher = None


def test_coalescing_under_load(searcher):
    """With many concurrent same-shape queries, launches < queries."""
    batcher = PlanBatcher()
    searcher.batcher = batcher
    texts = [" ".join(np.random.default_rng(i).choice(VOCAB, 2))
             for i in range(24)]
    # warm the compile cache so launches are fast enough to overlap
    searcher.query_phase(q("ant bee"), 10)

    threads = [threading.Thread(
        target=lambda t=t: searcher.query_phase(q(t), 10)) for t in texts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    searcher.batcher = None
    st = batcher.stats()
    assert st["batched_queries"] == len(texts) + 1
    # coalescing is timing-dependent; require only that batching occurred
    # without loss (every query answered exactly once)
    assert 1 <= st["launches"] <= st["batched_queries"]


def test_batcher_stats(searcher):
    batcher = PlanBatcher()
    searcher.batcher = batcher
    searcher.query_phase(q("ant"), 5)
    searcher.batcher = None
    assert batcher.stats()["launches"] == 1
    assert batcher.stats()["avg_batch"] == 1.0
