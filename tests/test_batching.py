"""Continuous-batching tests: concurrent plan-path searches coalesce into
shared launches and return exactly what solo execution returns."""

import threading

import numpy as np
import pytest

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.search.batching import PlanBatcher
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.search.queries import parse_query
from elasticsearch_tpu.search.searcher import ShardSearcher

MAPPINGS = {"properties": {"title": {"type": "text"},
                           "tag": {"type": "keyword"}}}
VOCAB = ["ant", "bee", "cat", "dog", "elk", "fox", "gnu", "hen", "ibis",
         "jay"]


@pytest.fixture(scope="module")
def searcher():
    rng = np.random.default_rng(3)
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i in range(200):
        doc = {"title": " ".join(rng.choice(VOCAB, rng.integers(2, 9))),
               "tag": str(rng.choice(["a", "b"]))}
        w.add(svc.parse(str(i), doc))
    seg = w.build("b0")
    return ShardSearcher([seg], svc, DeviceSegmentCache())


def q(text):
    return parse_query({"match": {"title": text}})


def test_batched_equals_solo(searcher):
    queries = [" ".join(pair) for pair in
               [("ant", "bee"), ("cat", "dog"), ("elk", "fox"),
                ("gnu", "hen"), ("ibis", "jay"), ("ant", "fox")]]
    solo = []
    searcher.batcher = None
    for text in queries:
        r = searcher.query_phase(q(text), 20)
        solo.append(([(d.segment_idx, d.docid, round(d.score, 4))
                      for d in r.docs], r.total_hits))

    searcher.batcher = PlanBatcher()
    results = [None] * len(queries)
    errs = []

    def run(i):
        try:
            r = searcher.query_phase(q(queries[i]), 20)
            results[i] = ([(d.segment_idx, d.docid, round(d.score, 4))
                           for d in r.docs], r.total_hits)
        except BaseException as exc:  # noqa: BLE001
            errs.append(exc)

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(queries))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert results == solo
    searcher.batcher = None


def test_coalescing_under_load(searcher):
    """With many concurrent same-shape queries, launches < queries."""
    batcher = PlanBatcher()
    searcher.batcher = batcher
    texts = [" ".join(np.random.default_rng(i).choice(VOCAB, 2))
             for i in range(24)]
    # warm the compile cache so launches are fast enough to overlap
    searcher.query_phase(q("ant bee"), 10)

    threads = [threading.Thread(
        target=lambda t=t: searcher.query_phase(q(t), 10)) for t in texts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    searcher.batcher = None
    st = batcher.stats()
    assert st["batched_queries"] == len(texts) + 1
    # coalescing is timing-dependent; require only that batching occurred
    # without loss (every query answered exactly once)
    assert 1 <= st["launches"] <= st["batched_queries"]


def test_batcher_stats(searcher):
    batcher = PlanBatcher()
    searcher.batcher = batcher
    searcher.query_phase(q("ant"), 5)
    searcher.batcher = None
    assert batcher.stats()["launches"] == 1
    assert batcher.stats()["avg_batch"] == 1.0


def _mk_synth_plan(nb_width, rng, d_bd, d_bt, d_lens, zero_block):
    """Fabricate a BoundPlan over the SHARED device corpus arrays with
    a specific padded selection width (the pow2 bucket bind_plan would
    pick). Sharing the arrays matters: batch signatures key on their
    identity, exactly like streams built from one DevicePostings."""
    import jax.numpy as jnp

    from elasticsearch_tpu.ops import plan as plan_ops
    from elasticsearch_tpu.search.plan import BoundPlan

    nsel = max(2, nb_width // 4)
    sel = np.full(nb_width, zero_block, np.int32)
    ws = np.zeros(nb_width, np.float32)
    sel[:nsel] = rng.choice(zero_block, nsel, replace=False)
    ws[:nsel] = rng.uniform(0.5, 2.0, nsel).astype(np.float32)
    grp = np.full(nb_width, 4, np.int32)
    grp[:nsel] = 0
    sub = np.zeros(nb_width, np.int32)
    sub[:nsel] = np.arange(nsel)
    const = np.zeros(nb_width, bool)
    stream = plan_ops.FieldStream(
        d_bd, d_bt, d_lens, jnp.float32(30.0), sel, grp, sub, ws, const)
    kind = np.full(4, plan_ops.FILTER, np.int32)
    req = np.full(4, 1 << 30, np.int32)
    cst = np.full(4, np.nan, np.float32)
    kind[0] = plan_ops.SHOULD
    req[0] = 1
    return BoundPlan([stream], kind, req, cst, None, 0, 0, 1, 0.0, 0.0,
                     "sum")


def test_mixed_nb_widths_share_cohort_and_stay_exact():
    """Two plans whose selections bound to DIFFERENT pow2 buckets (128
    vs 256 — same coalescing tier) share one batch signature and the
    padded cohort returns exactly what each plan returns solo."""
    from types import SimpleNamespace

    import jax.numpy as jnp

    from elasticsearch_tpu.ops import plan as plan_ops
    from elasticsearch_tpu.search.batching import PlanBatcher, _Entry

    rng = np.random.default_rng(9)
    nd, tb, blk = 2048, 320, 8
    bd = np.sort(rng.integers(0, nd, (tb, blk)).astype(np.int32), axis=1)
    bt = rng.integers(0, 4, (tb, blk)).astype(np.float32)
    bd = np.concatenate([bd, np.zeros((1, blk), np.int32)])
    bt = np.concatenate([bt, np.zeros((1, blk), np.float32)])
    lens = rng.integers(5, 60, nd).astype(np.float32)
    live = jnp.asarray(np.ones(nd, bool))
    d_bd = jnp.asarray(bd)
    d_bt = jnp.asarray(bt)
    d_lens = jnp.asarray(lens)

    bp_small = _mk_synth_plan(128, rng, d_bd, d_bt, d_lens, tb)
    bp_big = _mk_synth_plan(256, rng, d_bd, d_bt, d_lens, tb)
    ctx = SimpleNamespace(
        segment=SimpleNamespace(name="s0", live_version=0), live=live)

    batcher = PlanBatcher()
    sig_s = batcher._signature(bp_small, ctx, 10, 1.2, 0.75)
    sig_b = batcher._signature(bp_big, ctx, 10, 1.2, 0.75)
    assert sig_s == sig_b           # differing NB buckets coalesce

    def solo(bp):
        vals, ids, total = plan_ops.plan_topk(
            bp.streams, bp.group_kind, bp.group_req, bp.group_const,
            live, None, bp.n_must, bp.n_filter, bp.msm, k=10,
            combine=bp.combine)
        return (np.asarray(vals), np.asarray(ids), int(total))

    expected = [solo(bp_small), solo(bp_big)]
    entries = [_Entry(bp_small), _Entry(bp_big)]
    batcher._run(entries, ctx, 10, 1.2, 0.75)
    assert batcher.stats()["launches"] == 1
    assert batcher.stats()["batch_hist"] == {"2": 1}
    for e, (ev, ei, et) in zip(entries, expected):
        gv, gi, gt = e.result
        assert gt == et
        np.testing.assert_array_equal(gi, ei)
        np.testing.assert_allclose(gv, ev, rtol=1e-6)
