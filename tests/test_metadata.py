"""Aliases, templates, rollover, shrink/split, data streams (ref:
cluster/metadata/ — IndexAbstraction resolution, MetadataIndexTemplate-
Service, MetadataRolloverService, MetadataCreateDataStreamService,
TransportResizeAction)."""

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
)
from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def d(node, method, path, params=None, body=None):
    return node.rest_controller.dispatch(method, path, params or {}, body)


# ---------------------------------------------------------------- aliases

def test_alias_add_search_and_remove(node):
    d(node, "PUT", "/logs-1/_doc/1", {"refresh": "true"}, {"v": 1})
    d(node, "PUT", "/logs-2/_doc/2", {"refresh": "true"}, {"v": 2})
    status, _ = d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "logs-1", "alias": "logs"}},
        {"add": {"index": "logs-2", "alias": "logs"}}]})
    assert status == 200
    _, r = d(node, "POST", "/logs/_search", body={"size": 10})
    assert r["hits"]["total"]["value"] == 2
    # GET shapes
    _, r = d(node, "GET", "/_alias/logs")
    assert set(r) == {"logs-1", "logs-2"}
    # remove one member
    d(node, "POST", "/_aliases", body={"actions": [
        {"remove": {"index": "logs-1", "alias": "logs"}}]})
    _, r = d(node, "POST", "/logs/_search", body={})
    assert r["hits"]["total"]["value"] == 1


def test_alias_write_index_routing(node):
    d(node, "PUT", "/a-1", body={})
    d(node, "PUT", "/a-2", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "a-1", "alias": "a"}},
        {"add": {"index": "a-2", "alias": "a", "is_write_index": True}}]})
    d(node, "PUT", "/a/_doc/1", {"refresh": "true"}, {"v": 1})
    _, doc = d(node, "GET", "/a-2/_doc/1")
    assert doc["found"] is True


def test_filtered_alias(node):
    for i, team in enumerate(["red", "blue", "red"]):
        d(node, "PUT", f"/events/_doc/{i}", {"refresh": "true"},
          {"team": team, "n": i})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "events", "alias": "red_events",
                 "filter": {"term": {"team.keyword": "red"}}}}]})
    _, r = d(node, "POST", "/red_events/_search", body={})
    assert r["hits"]["total"]["value"] == 2


def test_alias_per_index_endpoint(node):
    d(node, "PUT", "/i1", body={})
    d(node, "PUT", "/i1/_alias/al", body={})
    _, r = d(node, "GET", "/i1/_alias")
    assert "al" in r["i1"]["aliases"]
    d(node, "DELETE", "/i1/_alias/al")
    _, r = d(node, "GET", "/i1/_alias")
    assert r["i1"]["aliases"] == {}


def test_alias_name_collision_rejected(node):
    d(node, "PUT", "/real", body={})
    d(node, "PUT", "/other", body={})
    with pytest.raises(IllegalArgumentException):
        node.metadata_service.update_aliases(
            [{"add": {"index": "other", "alias": "real"}}])


# --------------------------------------------------------------- templates

def test_index_template_applied_on_create(node):
    d(node, "PUT", "/_component_template/base", body={"template": {
        "settings": {"index.number_of_shards": 2},
        "mappings": {"properties": {"ts": {"type": "date"}}}}})
    d(node, "PUT", "/_index_template/logs", body={
        "index_patterns": ["logs-*"], "composed_of": ["base"],
        "priority": 10,
        "template": {"mappings": {
            "properties": {"level": {"type": "keyword"}}}}})
    # auto-create via write applies the template
    d(node, "PUT", "/logs-app/_doc/1", {"refresh": "true"},
      {"level": "info", "msg": "x"})
    idx = node.indices_service.get("logs-app")
    assert idx.num_shards == 2
    mapping = idx.mapper.to_mapping()["properties"]
    assert mapping["ts"]["type"] == "date"
    assert mapping["level"]["type"] == "keyword"


def test_template_priority(node):
    d(node, "PUT", "/_index_template/low", body={
        "index_patterns": ["x-*"], "priority": 1,
        "template": {"settings": {"index.number_of_shards": 1}}})
    d(node, "PUT", "/_index_template/high", body={
        "index_patterns": ["x-special-*"], "priority": 100,
        "template": {"settings": {"index.number_of_shards": 3}}})
    d(node, "PUT", "/x-special-1", body={})
    assert node.indices_service.get("x-special-1").num_shards == 3


def test_request_body_overrides_template(node):
    d(node, "PUT", "/_index_template/t", body={
        "index_patterns": ["y-*"],
        "template": {"settings": {"index.number_of_shards": 4}}})
    d(node, "PUT", "/y-1", body={"settings": {"index.number_of_shards": 1}})
    assert node.indices_service.get("y-1").num_shards == 1


def test_template_crud(node):
    d(node, "PUT", "/_index_template/t", body={"index_patterns": ["z-*"]})
    _, r = d(node, "GET", "/_index_template/t")
    assert r["index_templates"][0]["name"] == "t"
    d(node, "DELETE", "/_index_template/t")
    status, _ = d(node, "GET", "/_index_template")
    assert status == 200


def test_template_with_aliases(node):
    d(node, "PUT", "/_index_template/t", body={
        "index_patterns": ["w-*"],
        "template": {"aliases": {"w_all": {}}}})
    d(node, "PUT", "/w-1", body={})
    _, r = d(node, "GET", "/_alias/w_all")
    assert "w-1" in r


# ---------------------------------------------------------------- rollover

def test_rollover_alias(node):
    d(node, "PUT", "/app-000001", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "app-000001", "alias": "app",
                 "is_write_index": True}}]})
    for i in range(5):
        d(node, "PUT", f"/app/_doc/{i}", {"refresh": "true"}, {"v": i})
    # conditions not met: no rollover
    _, r = d(node, "POST", "/app/_rollover", body={
        "conditions": {"max_docs": 100}})
    assert r["rolled_over"] is False
    # conditions met
    _, r = d(node, "POST", "/app/_rollover", body={
        "conditions": {"max_docs": 3}})
    assert r["rolled_over"] is True
    assert r["old_index"] == "app-000001"
    assert r["new_index"] == "app-000002"
    # writes now land in the new index
    d(node, "PUT", "/app/_doc/new", {"refresh": "true"}, {"v": 99})
    _, doc = d(node, "GET", "/app-000002/_doc/new")
    assert doc["found"] is True
    # searches via alias cover both
    _, r = d(node, "POST", "/app/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 6


def test_rollover_requires_counted_name_or_new_index(node):
    d(node, "PUT", "/plain", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "plain", "alias": "p", "is_write_index": True}}]})
    with pytest.raises(IllegalArgumentException):
        node.metadata_service.rollover("p", {})
    _, r = d(node, "POST", "/p/_rollover/plain-next", body={})
    assert r["new_index"] == "plain-next"


# ------------------------------------------------------------ shrink/split

def test_shrink_and_split(node):
    d(node, "PUT", "/big", body={"settings": {"index.number_of_shards": 4}})
    for i in range(40):
        d(node, "PUT", f"/big/_doc/{i}", {}, {"n": i})
    d(node, "POST", "/big/_refresh")
    _, r = d(node, "PUT", "/big/_shrink/small", body={
        "settings": {"index.number_of_shards": 1}})
    assert r["acknowledged"] is True
    assert node.indices_service.get("small").num_shards == 1
    _, r = d(node, "POST", "/small/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 40
    _, r = d(node, "PUT", "/small/_split/wide", body={
        "settings": {"index.number_of_shards": 3}})
    assert node.indices_service.get("wide").num_shards == 3
    _, r = d(node, "POST", "/wide/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 40


def test_shrink_more_shards_rejected(node):
    d(node, "PUT", "/src2", body={"settings": {"index.number_of_shards": 2}})
    status, r = d(node, "PUT", "/src2/_shrink/dst2",
                  body={"settings": {"index.number_of_shards": 4}})
    assert status == 400


# ------------------------------------------------------------ data streams

def test_data_stream_lifecycle(node):
    d(node, "PUT", "/_index_template/metrics", body={
        "index_patterns": ["metrics-*"], "data_stream": {},
        "template": {"mappings": {
            "properties": {"value": {"type": "double"}}}}})
    status, _ = d(node, "PUT", "/_data_stream/metrics-cpu")
    assert status == 200
    _, r = d(node, "GET", "/_data_stream/metrics-cpu")
    ds = r["data_streams"][0]
    assert ds["generation"] == 1
    backing = ds["indices"][0]["index_name"]
    assert backing.startswith(".ds-metrics-cpu-")
    # writes land in the backing index
    d(node, "PUT", "/metrics-cpu/_doc/1", {"refresh": "true"},
      {"@timestamp": "2026-01-01T00:00:00Z", "value": 0.5})
    _, r = d(node, "POST", "/metrics-cpu/_search", body={})
    assert r["hits"]["total"]["value"] == 1
    # rollover
    _, r = d(node, "POST", "/metrics-cpu/_rollover", body={})
    assert r["rolled_over"] is True
    _, r = d(node, "GET", "/_data_stream/metrics-cpu")
    assert r["data_streams"][0]["generation"] == 2
    assert len(r["data_streams"][0]["indices"]) == 2
    # search covers all backing indices
    d(node, "PUT", "/metrics-cpu/_doc/2", {"refresh": "true"},
      {"@timestamp": "2026-01-02T00:00:00Z", "value": 0.7})
    _, r = d(node, "POST", "/metrics-cpu/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 2
    # delete removes backing indices
    d(node, "DELETE", "/_data_stream/metrics-cpu")
    assert not node.indices_service.has(backing)


def test_data_stream_requires_template(node):
    with pytest.raises(IllegalArgumentException):
        node.metadata_service.create_data_stream("unmatched")


def test_data_stream_duplicate_rejected(node):
    d(node, "PUT", "/_index_template/t", body={
        "index_patterns": ["s-*"], "data_stream": {}})
    d(node, "PUT", "/_data_stream/s-1")
    with pytest.raises(ResourceAlreadyExistsException):
        node.metadata_service.create_data_stream("s-1")


# ------------------------------------------------------------- persistence

def test_metadata_persists_across_restart(tmp_path):
    n1 = Node(data_path=str(tmp_path / "data"))
    d(n1, "PUT", "/idx", body={})
    d(n1, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "idx", "alias": "al"}}]})
    d(n1, "PUT", "/_index_template/t", body={"index_patterns": ["q-*"]})
    n1.close()
    n2 = Node(data_path=str(tmp_path / "data"))
    assert "al" in n2.metadata_service.aliases
    assert "t" in n2.metadata_service.index_templates
    _, r = d(n2, "POST", "/al/_search", body={})
    assert r["hits"]["total"]["value"] == 0
    n2.close()


# ----------------------------------------------- review regression tests

def test_delete_index_cleans_alias_and_stream_refs(node):
    d(node, "PUT", "/m-1", body={})
    d(node, "PUT", "/m-2", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "m-1", "alias": "m"}},
        {"add": {"index": "m-2", "alias": "m"}}]})
    d(node, "DELETE", "/m-1")
    _, r = d(node, "POST", "/m/_search", body={})
    assert r["hits"]["total"]["value"] == 0  # resolves, no 404
    assert "m-1" not in node.metadata_service.aliases["m"]


def test_count_and_msearch_apply_alias_filter(node):
    for i, team in enumerate(["red", "blue", "red"]):
        d(node, "PUT", f"/ev/_doc/{i}", {"refresh": "true"}, {"team": team})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "ev", "alias": "red_ev",
                 "filter": {"term": {"team.keyword": "red"}}}}]})
    _, r = d(node, "GET", "/red_ev/_count")
    assert r["count"] == 2
    _, r = d(node, "POST", "/_msearch", body=[
        {"index": "red_ev"}, {"size": 0}])
    assert r["responses"][0]["hits"]["total"]["value"] == 2


def test_doc_apis_resolve_alias(node):
    d(node, "PUT", "/w-1", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "w-1", "alias": "w", "is_write_index": True}}]})
    d(node, "PUT", "/w/_doc/1", {"refresh": "true"}, {"v": 1})
    _, doc = d(node, "GET", "/w/_doc/1")
    assert doc["found"] is True
    status, _ = d(node, "POST", "/w/_update/1", body={"doc": {"v": 2}})
    assert status == 200
    status, _ = d(node, "DELETE", "/w/_doc/1")
    assert status == 200


def test_create_index_colliding_with_alias_rejected(node):
    d(node, "PUT", "/backing", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "backing", "alias": "taken"}}]})
    status, _ = d(node, "PUT", "/taken", body={})
    assert status == 400


def test_alias_remove_must_exist(node):
    d(node, "PUT", "/i9", body={})
    status, _ = d(node, "POST", "/_aliases", body={"actions": [
        {"remove": {"index": "i9", "alias": "missing",
                    "must_exist": True}}]})
    assert status == 404
    # without must_exist: silently acknowledged
    status, _ = d(node, "POST", "/_aliases", body={"actions": [
        {"remove": {"index": "i9", "alias": "missing"}}]})
    assert status == 200


def test_resize_includes_unrefreshed_docs(node):
    d(node, "PUT", "/fresh", body={})
    for i in range(5):
        d(node, "PUT", f"/fresh/_doc/{i}", {}, {"n": i})  # no refresh
    d(node, "PUT", "/fresh/_shrink/fresh2", body={})
    _, r = d(node, "POST", "/fresh2/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 5


def test_wildcard_matches_aliases_and_streams(node):
    d(node, "PUT", "/app-a", body={})
    d(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "app-a", "alias": "logsalias"}}]})
    d(node, "PUT", "/app-a/_doc/1", {"refresh": "true"}, {"v": 1})
    _, r = d(node, "POST", "/logsal*/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 1
    d(node, "PUT", "/_index_template/t", body={
        "index_patterns": ["str-*"], "data_stream": {}})
    d(node, "PUT", "/_data_stream/str-one")
    d(node, "PUT", "/str-one/_doc/1", {"refresh": "true"},
      {"@timestamp": "2026-01-01T00:00:00Z"})
    _, r = d(node, "POST", "/str-*/_search", body={"size": 0})
    assert r["hits"]["total"]["value"] == 1
