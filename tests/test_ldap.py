"""LDAP/AD realm (xpack/security.py LdapRealm + common/ldap.py) against
an in-process LDAP fixture speaking real BER wire bytes, plus
transport-layer IP filtering (VERDICT r2 item 5).

Ref: x-pack/plugin/security/.../authc/ldap/LdapRealm.java:54 (bind +
group search feeding role mappings), .../transport/filter/IPFilter.java.
"""

import base64
import socket
import threading

import pytest

from elasticsearch_tpu.common.ldap import (
    APP_BIND_REQUEST,
    APP_BIND_RESPONSE,
    APP_SEARCH_DONE,
    APP_SEARCH_ENTRY,
    APP_SEARCH_REQUEST,
    APP_UNBIND_REQUEST,
    CTX_SIMPLE_AUTH,
    ENUMERATED,
    FILTER_AND,
    FILTER_EQUALITY,
    FILTER_OR,
    FILTER_PRESENT,
    SEQUENCE,
    LdapClient,
    ber_int,
    ber_str,
    parse_int,
    read_tlv,
    tlv,
)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


class LdapFixture:
    """A tiny LDAPv3 server: simple bind against a password book,
    subtree search with equality/present/and/or filters."""

    def __init__(self, directory, passwords):
        self.directory = directory      # dn -> {attr: [values]}
        self.passwords = passwords      # dn -> password
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._closed = False
        threading.Thread(target=self._accept, daemon=True).start()

    def close(self):
        self._closed = True
        self._srv.close()

    def _accept(self):
        while not self._closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = b""
        bound = False
        try:
            while True:
                while True:
                    if len(buf) >= 2:
                        try:
                            _tag, payload, end = read_tlv(buf, 0)
                            if end <= len(buf):
                                buf = buf[end:]
                                break
                        except IndexError:
                            pass
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                _, mid_pl, off = read_tlv(payload, 0)
                msgid = parse_int(mid_pl)
                op_tag, op_pl, _ = read_tlv(payload, off)
                if op_tag == APP_UNBIND_REQUEST:
                    return
                if op_tag == APP_BIND_REQUEST:
                    o = 0
                    _, _v, o = read_tlv(op_pl, o)           # version
                    _, dn_pl, o = read_tlv(op_pl, o)
                    atag, pw_pl, _ = read_tlv(op_pl, o)
                    dn = dn_pl.decode()
                    pw = pw_pl.decode()
                    ok = (atag == CTX_SIMPLE_AUTH and dn
                          and self.passwords.get(dn) == pw and pw)
                    code = 0 if ok else 49   # invalidCredentials
                    bound = bool(ok)
                    resp = tlv(APP_BIND_RESPONSE,
                               ber_int(code, ENUMERATED)
                               + ber_str("") + ber_str(""))
                    conn.sendall(tlv(SEQUENCE, ber_int(msgid) + resp))
                    continue
                if op_tag == APP_SEARCH_REQUEST:
                    o = 0
                    _, base_pl, o = read_tlv(op_pl, o)
                    _, _scope, o = read_tlv(op_pl, o)
                    _, _deref, o = read_tlv(op_pl, o)
                    _, _sz, o = read_tlv(op_pl, o)
                    _, _tm, o = read_tlv(op_pl, o)
                    _, _types, o = read_tlv(op_pl, o)
                    ftag, f_pl, o = read_tlv(op_pl, o)
                    base = base_pl.decode().lower()
                    for dn, attrs in self.directory.items():
                        if not dn.lower().endswith(base):
                            continue
                        if not self._match((ftag, f_pl), dn, attrs):
                            continue
                        attr_seq = b"".join(
                            tlv(SEQUENCE, ber_str(a)
                                + tlv(0x31, b"".join(ber_str(v)
                                                     for v in vals)))
                            for a, vals in attrs.items())
                        entry = tlv(APP_SEARCH_ENTRY,
                                    ber_str(dn) + tlv(SEQUENCE, attr_seq))
                        conn.sendall(tlv(SEQUENCE,
                                         ber_int(msgid) + entry))
                    done = tlv(APP_SEARCH_DONE,
                               ber_int(0, ENUMERATED)
                               + ber_str("") + ber_str(""))
                    conn.sendall(tlv(SEQUENCE, ber_int(msgid) + done))
                    continue
                return   # unsupported op: drop the connection
        except OSError:
            pass
        finally:
            conn.close()
            del bound

    def _match(self, flt, dn, attrs) -> bool:
        tag, pl = flt
        if tag == FILTER_EQUALITY:
            _, a_pl, o = read_tlv(pl, 0)
            _, v_pl, _ = read_tlv(pl, o)
            attr, want = a_pl.decode(), v_pl.decode()
            return want in attrs.get(attr, [])
        if tag == FILTER_PRESENT:
            return pl.decode() in attrs
        if tag in (FILTER_AND, FILTER_OR):
            subs = []
            o = 0
            while o < len(pl):
                t, sp, o2 = read_tlv(pl, o)
                subs.append(self._match((t, sp), dn, attrs))
                o = o2
            return all(subs) if tag == FILTER_AND else any(subs)
        return False


PEOPLE = "ou=people,dc=acme,dc=com"
GROUPS = "ou=groups,dc=acme,dc=com"


@pytest.fixture()
def ldap_server():
    srv = LdapFixture(
        directory={
            f"uid=jdoe,{PEOPLE}": {"uid": ["jdoe"], "cn": ["John Doe"]},
            f"uid=asmith,{PEOPLE}": {"uid": ["asmith"],
                                     "cn": ["Alice Smith"]},
            f"cn=monitoring,{GROUPS}": {
                "cn": ["monitoring"],
                "member": [f"uid=jdoe,{PEOPLE}"]},
            f"cn=admins,{GROUPS}": {
                "cn": ["admins"],
                "memberUid": ["asmith"]},
        },
        passwords={f"uid=jdoe,{PEOPLE}": "jpw",
                   f"uid=asmith,{PEOPLE}": "apw",
                   f"cn=svc,{PEOPLE}": "svcpw"})
    # service account for search-then-bind
    srv.directory[f"cn=svc,{PEOPLE}"] = {"cn": ["svc"]}
    yield srv
    srv.close()


def test_ber_client_roundtrip(ldap_server):
    c = LdapClient("127.0.0.1", ldap_server.port)
    assert c.simple_bind(f"uid=jdoe,{PEOPLE}", "jpw")
    assert not c.simple_bind(f"uid=jdoe,{PEOPLE}", "wrong")
    hits = c.search(GROUPS, ("=", "member", f"uid=jdoe,{PEOPLE}"),
                    ["cn"])
    assert [dn for dn, _ in hits] == [f"cn=monitoring,{GROUPS}"]
    assert hits[0][1]["cn"] == ["monitoring"]
    # compound filter
    hits = c.search(GROUPS, ("|", [("=", "memberUid", "asmith"),
                                   ("=", "member", "nobody")]), ["cn"])
    assert [dn for dn, _ in hits] == [f"cn=admins,{GROUPS}"]
    c.close()
    from elasticsearch_tpu.common.ldap import LdapError
    c2 = LdapClient("127.0.0.1", ldap_server.port)
    with pytest.raises(LdapError):
        c2.simple_bind(f"uid=jdoe,{PEOPLE}", "")   # refused client-side
    c2.close()


def _node(tmp_path, ldap_port, **extra):
    cfg = {"url": f"ldap://127.0.0.1:{ldap_port}",
           "user_dn_templates": [f"uid={{0}},{PEOPLE}"],
           "group_search_base": GROUPS}
    cfg.update(extra)
    return Node(settings=Settings.from_dict({
        "xpack": {"security": {"enabled": True,
                               "authc": {"ldap": cfg}}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))


def basic(user, pw):
    return {"Authorization": "Basic "
            + base64.b64encode(f"{user}:{pw}".encode()).decode()}


def call(node, method, path, body=None, headers=None, expect=200):
    status, r = node.rest_controller.dispatch(method, path, {}, body,
                                              headers=headers)
    assert status == expect, (status, r)
    return r


def test_ldap_realm_bind_and_group_roles(tmp_path, ldap_server):
    node = _node(tmp_path, ldap_server.port)
    try:
        # group → role mapping (ref: ExpressionRoleMapping groups field)
        call(node, "PUT", "/_security/role_mapping/ldap-mon",
             {"roles": ["monitoring_user"],
              "rules": {"field": {"groups": f"cn=monitoring,{GROUPS}"}}},
             headers=basic("elastic", "s3cret"))
        me = call(node, "GET", "/_security/_authenticate",
                  headers=basic("jdoe", "jpw"))
        assert me["username"] == "jdoe"
        assert "monitoring_user" in me["roles"]
        # the granted role authorizes cluster reads
        call(node, "GET", "/_cluster/health",
             headers=basic("jdoe", "jpw"))
        # wrong password refused
        call(node, "GET", "/_security/_authenticate",
             headers=basic("jdoe", "nope"), expect=401)
        # EMPTY password must not become an unauthenticated bind
        call(node, "GET", "/_security/_authenticate",
             headers=basic("jdoe", ""), expect=401)
        # unknown user refused
        call(node, "GET", "/_security/_authenticate",
             headers=basic("ghost", "x"), expect=401)
    finally:
        node.close()


def test_ldap_search_then_bind(tmp_path, ldap_server):
    node = _node(tmp_path, ldap_server.port,
                 user_dn_templates=None,
                 bind_dn=f"cn=svc,{PEOPLE}", bind_password="svcpw",
                 user_search_base=PEOPLE)
    try:
        call(node, "PUT", "/_security/role_mapping/ldap-adm",
             {"roles": ["superuser"],
              "rules": {"field": {"groups": "cn=admins,*"}}},
             headers=basic("elastic", "s3cret"))
        me = call(node, "GET", "/_security/_authenticate",
                  headers=basic("asmith", "apw"))
        assert me["username"] == "asmith"
        assert "superuser" in me["roles"]
        call(node, "GET", "/_security/_authenticate",
             headers=basic("asmith", "bad"), expect=401)
    finally:
        node.close()


def test_native_realm_still_wins_first(tmp_path, ldap_server):
    """Realm ORDER: native resolves its own users before LDAP sees the
    credential (the chain contract)."""
    node = _node(tmp_path, ldap_server.port)
    try:
        me = call(node, "GET", "/_security/_authenticate",
                  headers=basic("elastic", "s3cret"))
        assert me["username"] == "elastic"
    finally:
        node.close()


def test_transport_ip_filter_rejects_at_accept():
    from elasticsearch_tpu.transport.transport import (DiscoveryNode,
                                                       TcpTransport)
    t = TcpTransport(
        DiscoveryNode(node_id="n1", name="n1", host="127.0.0.1", port=0),
        ip_filter=("10.0.0.0/8", ""))   # allow-only ⇒ loopback denied
    try:
        s = socket.create_connection(("127.0.0.1", t.bound_port),
                                     timeout=3)
        s.settimeout(3)
        # the accept loop closes us without a byte
        assert s.recv(1) == b""
        s.close()
    finally:
        t.close()
