"""v2 serving kernel (merge candidates + f64 re-rank) vs the exact v1
kernel: identical certified outputs, honest ok=0 on tie-mass corpora."""

import numpy as np
import pytest

import jax.numpy as jnp

from elasticsearch_tpu.ops import fastpath as fp

BLOCK = 128


def build_segment(rng, n_docs, n_terms, df_range=(40, 400)):
    """Block-layout postings like index/segment.py builds them."""
    tbs, nb = [], []
    blocks_d, blocks_t = [], []
    flat_d, flat_t = [], []
    next_block = 0
    for t in range(n_terms):
        df = int(rng.integers(*df_range))
        docs = np.sort(rng.choice(n_docs, size=df, replace=False)
                       ).astype(np.int32)
        tfs = rng.integers(1, 5, size=df).astype(np.float32)
        nblk = (df + BLOCK - 1) // BLOCK
        tbs.append(next_block)
        nb.append(nblk)
        next_block += nblk
        pad = nblk * BLOCK - df
        d = np.concatenate([docs, np.zeros(pad, np.int32)])
        f = np.concatenate([tfs, np.zeros(pad, np.float32)])
        blocks_d.append(d.reshape(nblk, BLOCK))
        blocks_t.append(f.reshape(nblk, BLOCK))
    # reserved zero block
    blocks_d.append(np.zeros((1, BLOCK), np.int32))
    blocks_t.append(np.zeros((1, BLOCK), np.float32))
    bd = np.concatenate(blocks_d)
    bt = np.concatenate(blocks_t)
    lens = rng.integers(5, 80, size=n_docs).astype(np.float32)
    return dict(bd=bd, bt=bt, tbs=np.asarray(tbs), nb=np.asarray(nb),
                zero_block=bd.shape[0] - 1, lens=lens,
                flat_d=bd.reshape(-1), flat_t=bt.reshape(-1),
                avg=float(lens.mean()))


def slotted_sel(seg, term_ids, idf, n_slots, nb_bucket):
    """Each term-instance run starts on a slot boundary."""
    slot_blocks = nb_bucket // n_slots
    sel = np.full(nb_bucket, seg["zero_block"], np.int32)
    ws = np.zeros(nb_bucket, np.float32)
    ts = np.zeros(fp.MAX_T, np.int32)
    tl = np.zeros(fp.MAX_T, np.int32)
    ti = np.zeros(fp.MAX_T, np.float64)
    pos = 0
    for i, t in enumerate(term_ids):
        cnt = int(seg["nb"][t])
        start = int(seg["tbs"][t])
        need = -(-cnt // slot_blocks) * slot_blocks
        assert pos + need <= nb_bucket
        sel[pos:pos + cnt] = np.arange(start, start + cnt)
        ws[pos:pos + cnt] = np.float32(idf[t])
        pos += need
        ts[i] = start * BLOCK
        tl[i] = int((seg["bt"][start:start + cnt] > 0).sum())
        ti[i] = idf[t]
    return sel, ws, ts, tl, ti


def flat_sel(seg, term_ids, idf, nb_bucket):
    sel = np.full(nb_bucket, seg["zero_block"], np.int32)
    ws = np.zeros(nb_bucket, np.float64)
    pos = 0
    for t in term_ids:
        cnt = int(seg["nb"][t])
        start = int(seg["tbs"][t])
        sel[pos:pos + cnt] = np.arange(start, start + cnt)
        ws[pos:pos + cnt] = idf[t]
        pos += cnt
    return sel, ws


def run_both(seg, queries, n_docs=2000, k=50,
             n_slots=8, nb_bucket=64):
    q_n = len(queries)
    idf = np.log1p(n_docs / (seg["nb"] * BLOCK))
    masks = np.ones((fp.F_SLOTS, n_docs), bool)
    mask_ids = np.zeros(q_n, np.int32)
    sel2 = np.zeros((q_n, nb_bucket), np.int32)
    ws2 = np.zeros((q_n, nb_bucket), np.float32)
    ts2 = np.zeros((q_n, fp.MAX_T), np.int32)
    tl2 = np.zeros((q_n, fp.MAX_T), np.int32)
    ti2 = np.zeros((q_n, fp.MAX_T), np.float64)
    sel1 = np.zeros((q_n, nb_bucket), np.int32)
    ws1 = np.zeros((q_n, nb_bucket), np.float64)
    for qi, terms in enumerate(queries):
        s, w, ts, tl, ti = slotted_sel(seg, terms, idf, n_slots,
                                       nb_bucket)
        sel2[qi], ws2[qi], ts2[qi], tl2[qi], ti2[qi] = s, w, ts, tl, ti
        s1, w1 = flat_sel(seg, terms, idf, nb_bucket)
        sel1[qi], ws1[qi] = s1, w1
    import jax
    wd = np.float64 if jax.config.jax_enable_x64 else np.float32
    out1 = np.asarray(fp.bm25_topk_total_batch(
        seg["bd"], seg["bt"], jnp.asarray(sel1), jnp.asarray(
            ws1.astype(wd)),
        seg["lens"], jnp.asarray(masks), jnp.asarray(mask_ids),
        wd(seg["avg"]), 1.2, 0.75, k))
    out2 = np.asarray(fp.bm25_candidates_rerank_batch(
        seg["bd"], seg["bt"], seg["flat_d"], seg["flat_t"],
        jnp.asarray(sel2), jnp.asarray(ws2), seg["lens"],
        jnp.asarray(masks), jnp.asarray(mask_ids), jnp.asarray(ts2),
        jnp.asarray(tl2), jnp.asarray(ti2.astype(wd)), wd(seg["avg"]),
        n_slots, 1.2, 0.75, k))
    return out1, out2


def unpack1(row, k):
    return (row[:k], row[k:2 * k].astype(np.int32),
            int(row[2 * k:].astype(np.int32)[0]))


def _norm_hits(vals, ids, k):
    """Canonical (score desc, docid asc) order for comparison — v1
    leaves device tie order arbitrary (host re-sorts); v2 is already
    contract-ordered."""
    fin = np.isfinite(vals)
    v, d = vals[fin], ids[fin]
    order = np.lexsort((d, -v))
    return v[order], d[order]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_v2_matches_v1(seed):
    rng = np.random.default_rng(seed)
    n_docs = 2000
    seg = build_segment(rng, n_docs, n_terms=12)
    queries = [list(rng.choice(12, size=int(rng.integers(1, 6)),
                               replace=False))
               for _ in range(4)]
    out1, out2 = run_both(seg, queries, n_docs=n_docs)
    k = 50
    for qi in range(len(queries)):
        v1, d1, t1 = unpack1(out1[qi], k)
        v2 = out2[qi][:k]
        d2 = out2[qi][k:2 * k].astype(np.int32)
        t2 = int(out2[qi][2 * k])
        ok = int(np.asarray(out2[qi][2 * k + 1],
                            np.float32).astype(np.int32))
        assert ok == 1, f"q{qi} uncertified on a benign corpus"
        assert t1 == t2, (qi, t1, t2)
        nv1, nd1 = _norm_hits(v1, d1, k)
        nv2, nd2 = _norm_hits(v2, d2, k)
        np.testing.assert_array_equal(nd1, nd2)
        np.testing.assert_allclose(nv1, nv2, rtol=1e-6)


def test_v2_duplicate_term_instances():
    rng = np.random.default_rng(3)
    seg = build_segment(rng, 1000, n_terms=6)
    out1, out2 = run_both(seg, [[2, 2, 5], [0, 1, 2, 3, 4, 5]],
                          n_docs=1000)
    k = 50
    for qi in range(2):
        v1, d1, _ = unpack1(out1[qi], k)
        v2 = out2[qi][:k]
        d2 = out2[qi][k:2 * k].astype(np.int32)
        nv1, nd1 = _norm_hits(v1, d1, k)
        nv2, nd2 = _norm_hits(v2, d2, k)
        np.testing.assert_array_equal(nd1, nd2)
        np.testing.assert_allclose(nv1, nv2, rtol=1e-6)


def test_v2_bucket_slot_fit_routing():
    """Slot-fit math: Σ ceil(blocks/slot) <= N_SLOTS picks the smallest
    bucket; misfits return None (served by the warmed v1 shape)."""
    from elasticsearch_tpu.search.fastpath import FastPathServer
    srv = FastPathServer.__new__(FastPathServer)
    srv.nb_buckets = (1024, 4096)
    nbs = np.zeros(40, np.int64)
    reg = {"nb": nbs}
    # 4 tiny terms: 4 slots of 64 at bucket 1024
    nbs[:4] = 10
    assert srv._v2_bucket(reg, [0, 1, 2, 3]) == 1024
    # one 300-block term: ceil(300/64)=5 slots -> still bucket 1024
    nbs[4] = 300
    assert srv._v2_bucket(reg, [4]) == 1024
    # 16 terms of 300 blocks: 5 slots each at 1024 (80>16); at 4096
    # slot=256 -> 2 slots each (32>16) -> misfit
    nbs[5:21] = 300
    assert srv._v2_bucket(reg, list(range(5, 21))) is None
    # 16 terms of <=256 blocks fit bucket 4096 exactly (1 slot each)
    nbs[21:37] = 256
    assert srv._v2_bucket(reg, list(range(21, 37))) == 4096
    # 17 instances can never fit
    assert srv._v2_bucket(reg, [0] * 17) is None
    # all-unknown terms -> None (no device work)
    assert srv._v2_bucket(reg, [-1, -1]) is None


def test_v2_slotted_assembly_runs_stay_sorted():
    """Each term-instance run starts at a slot boundary and padding
    lanes key to SENT — every slot must be ascending (the merge
    precondition)."""
    rng = np.random.default_rng(9)
    seg = build_segment(rng, 1500, n_terms=5, df_range=(100, 500))
    idf = np.log1p(1500 / (seg["nb"] * BLOCK))
    n_slots, nb_bucket = 8, 64
    sel, ws, *_ = slotted_sel(seg, [0, 3, 4], idf, n_slots, nb_bucket)
    d = seg["bd"][sel]              # [NB, B]
    tf = seg["bt"][sel]
    keys = np.where(tf > 0, d, 0x7FFFFFFF).reshape(n_slots, -1)
    for s in range(n_slots):
        assert np.all(np.diff(keys[s].astype(np.int64)) >= 0), s


def test_v2_mass_ties_refuse_certificate():
    """Degenerate corpus: every matching doc scores identically and the
    tie class is far wider than CAND_V2 — v2 must set ok=0 (refire),
    never emit a possibly-wrong certified result."""
    n_docs = 8192
    # one term matching EVERY doc with tf=1, uniform doc length
    docs = np.arange(n_docs, dtype=np.int32)
    nblk = n_docs // BLOCK
    bd = np.concatenate([docs.reshape(nblk, BLOCK),
                         np.zeros((1, BLOCK), np.int32)])
    bt = np.concatenate([np.ones((nblk, BLOCK), np.float32),
                         np.zeros((1, BLOCK), np.float32)])
    seg = dict(bd=bd, bt=bt, tbs=np.asarray([0]), nb=np.asarray([nblk]),
               zero_block=nblk, lens=np.full(n_docs, 10.0, np.float32),
               flat_d=bd.reshape(-1), flat_t=bt.reshape(-1), avg=10.0)
    out1, out2 = run_both(seg, [[0]], n_docs=n_docs, nb_bucket=64)
    k = 50
    ok = int(np.asarray(out2[0][2 * k + 1], np.float32).astype(np.int32))
    assert ok == 0
