"""Watcher + monitoring plugin tests (model: x-pack watcher execution
tests and monitoring collector/exporter tests)."""

import time

import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, expect=200, **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, r
    return r


def _errors_index(node, n_errors=3):
    node.indices_service.create_index("logs", {}, {
        "properties": {"level": {"type": "keyword"},
                       "msg": {"type": "text"}}})
    idx = node.indices_service.get("logs")
    for i in range(n_errors):
        idx.index_doc(f"e{i}", {"level": "error", "msg": f"boom {i}"})
    idx.index_doc("ok", {"level": "info", "msg": "fine"})
    idx.refresh()


WATCH = {
    "trigger": {"schedule": {"interval": "10m"}},
    "input": {"search": {"request": {
        "indices": ["logs"],
        "body": {"query": {"term": {"level": {"value": "error"}}},
                 "size": 0, "track_total_hits": True}}}},
    "condition": {"compare": {
        "payload.hits.total.value": {"gte": 3}}},
    "actions": {
        "note": {"logging": {
            "text": "found {{ctx.payload.hits.total.value}} errors"}},
        "store": {"index": {"index": "alerts"}},
    },
}


def test_watch_crud(node):
    r = call(node, "PUT", "/_watcher/watch/errors", WATCH, expect=201)
    assert r["created"] is True
    r = call(node, "GET", "/_watcher/watch/errors")
    assert r["watch"]["condition"] == WATCH["condition"]
    r = call(node, "PUT", "/_watcher/watch/errors", WATCH, expect=201)
    assert r["created"] is False
    call(node, "DELETE", "/_watcher/watch/errors")
    call(node, "GET", "/_watcher/watch/errors", expect=404)


def test_watch_execute_condition_met(node):
    _errors_index(node)
    call(node, "PUT", "/_watcher/watch/errors", WATCH, expect=201)
    r = call(node, "POST", "/_watcher/watch/errors/_execute")
    rec = r["watch_record"]
    assert rec["state"] == "executed"
    assert rec["result"]["condition"]["met"] is True
    logging_result = next(a for a in rec["result"]["actions"]
                          if a["id"] == "note")
    assert logging_result["logging"]["logged_text"] == "found 3 errors"
    # the index action wrote an alert doc
    r = node.search_service.search("alerts", {"size": 10})
    assert r["hits"]["total"]["value"] == 1
    assert r["hits"]["hits"][0]["_source"]["watch_id"] == "errors"


def test_watch_execute_condition_not_met(node):
    _errors_index(node, n_errors=1)
    call(node, "PUT", "/_watcher/watch/errors", WATCH, expect=201)
    r = call(node, "POST", "/_watcher/watch/errors/_execute")
    assert r["watch_record"]["state"] == "execution_not_needed"
    assert "alerts" not in node.indices_service.indices


def test_watch_scheduler_fires(node):
    _errors_index(node)
    w = dict(WATCH)
    w["trigger"] = {"schedule": {"interval": "200ms"}}
    call(node, "PUT", "/_watcher/watch/fast", w, expect=201)
    deadline = time.time() + 5
    # poll on the SEARCHABLE history count — index membership flips
    # before the record is indexed+refreshed, so anything less races
    # the executing tick
    history_total = 0
    while time.time() < deadline:
        if ".watcher-history" in node.indices_service.indices:
            r = node.search_service.search(".watcher-history",
                                           {"size": 10})
            history_total = r["hits"]["total"]["value"]
            if history_total >= 1:
                break
        time.sleep(0.1)
    assert "alerts" in node.indices_service.indices
    assert history_total >= 1


def test_watch_activate_deactivate(node):
    call(node, "PUT", "/_watcher/watch/w1", WATCH, expect=201)
    r = call(node, "PUT", "/_watcher/watch/w1/_deactivate")
    assert r["status"]["state"]["active"] is False
    r = call(node, "PUT", "/_watcher/watch/w1/_activate")
    assert r["status"]["state"]["active"] is True


def test_watch_script_condition_and_stats(node):
    _errors_index(node)
    w = dict(WATCH)
    w["condition"] = {"script": "ctx.payload.hits.total.value > 2"}
    call(node, "PUT", "/_watcher/watch/s1", w, expect=201)
    r = call(node, "POST", "/_watcher/watch/s1/_execute")
    assert r["watch_record"]["state"] == "executed"
    stats = call(node, "GET", "/_watcher/stats")
    assert stats["execution_count"] >= 1
    assert stats["watch_count"] == 1


def test_monitoring_collect_and_bulk(node):
    _errors_index(node)
    r = call(node, "POST", "/_monitoring/_collect")
    assert r["collected"] >= 2              # index_stats + node_stats
    got = node.search_service.search(".monitoring-es", {
        "size": 50, "query": {"term": {"type.keyword": {"value": "node_stats"}}}})
    assert got["hits"]["total"]["value"] == 1
    src = got["hits"]["hits"][0]["_source"]
    assert src["node_stats"]["indices"]["docs"]["count"] == 4

    call(node, "POST", "/_monitoring/bulk",
         [{"type": "kibana_stats", "kibana": {"uuid": "k1"}}],
         system_id="kibana")
    got = node.search_service.search(".monitoring-es", {
        "size": 50, "query": {"term": {"type.keyword": {"value": "kibana_stats"}}}})
    assert got["hits"]["total"]["value"] == 1


def test_watch_script_condition_is_sandboxed(node):
    _errors_index(node)
    w = dict(WATCH)
    # an interpreter-escape attempt must evaluate to False, not execute
    w["condition"] = {"script":
                      "().__class__.__base__.__subclasses__()"}
    call(node, "PUT", "/_watcher/watch/evil", w, expect=201)
    r = call(node, "POST", "/_watcher/watch/evil/_execute")
    assert r["watch_record"]["state"] == "execution_not_needed"


def test_webhook_renders_full_request(node):
    """The webhook action renders the COMPLETE HTTP request the
    reference would send — URL, mustache-templated path/body, params,
    and basic-auth header — before recording it (zero-egress); the
    rendering is the testable contract (ref:
    actions/webhook/ExecutableWebhookAction + HttpRequestTemplate)."""
    call(node, "PUT", "/_watcher/watch/hook", {
        "trigger": {"schedule": {"interval": "1h"}},
        "input": {"simple": {"severity": "high", "count": 7}},
        "condition": {"always": {}},
        "actions": {"notify": {"webhook": {
            "method": "POST",
            "host": "alerts.example.com",
            "port": 8443,
            "scheme": "https",
            "path": "/alert/{{ctx.watch_id}}",
            "params": {"severity": "{{ctx.payload.severity}}"},
            "headers": {"Content-Type": "application/json"},
            "auth": {"basic": {"username": "hookuser",
                               "password": "hookpw"}},
            "body": "count={{ctx.payload.count}}",
        }}}}, expect=201)
    r = call(node, "POST", "/_watcher/watch/hook/_execute")
    action = r["watch_record"]["result"]["actions"][0]
    assert action["type"] == "webhook"
    req = action["webhook"]["request"]
    assert req["url"] == "https://alerts.example.com:8443/alert/hook"
    assert req["method"] == "POST"
    assert req["params"] == {"severity": "high"}
    assert req["body"] == "count=7"
    import base64
    expected = "Basic " + base64.b64encode(b"hookuser:hookpw").decode()
    assert req["headers"]["Authorization"] == expected
    assert req["headers"]["Content-Type"] == "application/json"
    # the rendered request is retained for inspection
    svc = node.watcher_service
    assert svc.webhook_requests[-1]["watch_id"] == "hook"
