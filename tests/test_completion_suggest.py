"""Completion suggester on the weighted prefix index (ref: search/
suggest/completion/CompletionSuggester.java:41 — Lucene NRT FSTs; here
sorted inputs + a max-weight segment tree, the same sublinear top-k)."""

import time

import numpy as np
import pytest

from elasticsearch_tpu.index.segment import CompletionValues
from elasticsearch_tpu.node import Node


def call(node, method, path, body=None, expect=(200, 201), **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    ok = (status in expect) if isinstance(expect, tuple) \
        else status == expect
    assert ok, (status, r)
    return r


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def _index_songs(node):
    call(node, "PUT", "/music", {"mappings": {"properties": {
        "suggest": {"type": "completion",
                    "contexts": [{"name": "genre",
                                  "type": "category"}]}}}})
    songs = [
        ("1", ["Nevermind", "Nirvana Nevermind"], 10, {"genre": "rock"}),
        ("2", ["Nevermore"], 5, {"genre": "metal"}),
        ("3", ["Neverland Express"], 7, {"genre": "pop"}),
        ("4", ["Nebraska"], 9, {"genre": "rock"}),
        ("5", ["Morning Phase"], 3, {"genre": "rock"}),
    ]
    for _id, inputs, w, ctx in songs:
        call(node, "PUT", f"/music/_doc/{_id}", {
            "suggest": {"input": inputs, "weight": w, "contexts": ctx}})
    call(node, "POST", "/music/_refresh")


def _suggest(node, body):
    return call(node, "POST", "/music/_search",
                {"size": 0, "suggest": body})["suggest"]


def test_completion_orders_by_weight(node):
    _index_songs(node)
    s = _suggest(node, {"s": {"prefix": "Nev",
                              "completion": {"field": "suggest"}}})
    texts = [o["text"] for o in s["s"][0]["options"]]
    assert texts == ["Nevermind", "Neverland Express", "Nevermore"]
    scores = [o["score"] for o in s["s"][0]["options"]]
    assert scores == [10.0, 7.0, 5.0]


def test_completion_context_filter(node):
    _index_songs(node)
    s = _suggest(node, {"s": {"prefix": "Ne", "completion": {
        "field": "suggest", "size": 10,
        "contexts": {"genre": ["rock"]}}}})
    texts = [o["text"] for o in s["s"][0]["options"]]
    assert texts == ["Nevermind", "Nebraska"]


def test_completion_multiple_inputs_and_delete(node):
    _index_songs(node)
    s = _suggest(node, {"s": {"prefix": "Nirvana",
                              "completion": {"field": "suggest"}}})
    assert [o["text"] for o in s["s"][0]["options"]] == \
        ["Nirvana Nevermind"]
    call(node, "DELETE", "/music/_doc/1")
    call(node, "POST", "/music/_refresh")
    s = _suggest(node, {"s": {"prefix": "Nev",
                              "completion": {"field": "suggest"}}})
    texts = [o["text"] for o in s["s"][0]["options"]]
    assert "Nevermind" not in texts


def test_million_entry_prefix_index_is_sublinear():
    """1M entries: exact top-k vs brute force, with a latency bound —
    the VERDICT r4 item-8 acceptance (linear scans measure ~100x this
    bound at 1M)."""
    rng = np.random.default_rng(7)
    n = 1_000_000
    # heavy shared-prefix load: 26^3 three-letter stems
    stems = [f"{a}{b}{c}"
             for a in "abcdefghijklmnopqrstuvwxyz"
             for b in "abcdefghijklmnopqrstuvwxyz"
             for c in "abcdefghijklmnopqrstuvwxyz"]
    suffix = rng.integers(0, 99999, n)
    inputs = [f"{stems[i % len(stems)]}{suffix[i]:05d}"
              for i in range(n)]
    weights = rng.random(n) * 1000
    t0 = time.time()
    cv = CompletionValues("s", inputs, weights,
                          np.zeros(n, np.int32))
    build_s = time.time() - t0
    live = np.ones(1, bool)

    # the densest prefix: 'a' covers ~1/26 of the corpus
    t0 = time.time()
    top = cv.top_k("a", 10, live=live)
    dt_dense = time.time() - t0
    # exactness vs brute force over the range
    import bisect
    lo = bisect.bisect_left(cv.inputs, "a")
    hi = bisect.bisect_left(cv.inputs, "a￿")
    order = sorted(range(lo, hi),
                   key=lambda i: (-cv.weights[i], cv.inputs[i]))[:10]
    assert top == order

    t_many = time.time()
    for stem in ("abc", "zzz", "mid", "qua", "not-there"):
        cv.top_k(stem, 10, live=live)
    dt_five = time.time() - t_many
    # generous CI bounds; a linear scan over 1M strings costs ~200ms+
    # per query on this hardware
    assert dt_dense < 0.05, f"dense-prefix top-k took {dt_dense:.3f}s"
    assert dt_five < 0.1, f"5 queries took {dt_five:.3f}s"
    assert build_s < 60


def test_completion_survives_flush_and_restart(tmp_path):
    """The weighted prefix index persists through segment save/load
    (flush + node restart) — suggestions must not vanish on reboot."""
    n = Node(data_path=str(tmp_path / "data"))
    try:
        _index_songs(n)
        call(n, "POST", "/music/_flush")
    finally:
        n.close()
    n2 = Node(data_path=str(tmp_path / "data"))
    try:
        s = call(n2, "POST", "/music/_search", {"size": 0, "suggest": {
            "s": {"prefix": "Nev",
                  "completion": {"field": "suggest",
                                 "contexts": {"genre": "rock"}}}}})
        opts = s["suggest"]["s"][0]["options"]
        assert [o["text"] for o in opts] == ["Nevermind"]
        assert opts[0]["score"] == 10.0
    finally:
        n2.close()
