"""Backpressure & memory protection: circuit breakers on the live path,
indexing pressure on the write path, and graceful shedding under
memory-pressure fault injection.

The contract under test (ref: HierarchyCircuitBreakerService +
IndexingPressure semantics):

- inbound transport messages charge ``in_flight_requests`` and release
  on completion; a trip is a typed, RETRYABLE failure the coordinator
  fails over to another copy (partial results, never a crash/hang);
- bulks charge coordinating/primary/replica in-flight bytes and get
  retryable 429s past the limit — used bytes return to ZERO once every
  in-flight operation completes (release-on-completion invariant);
- a replica 429 is NOT a stale copy: the primary retries with backoff
  and never reports shard-failed to the master for backpressure;
- HBM admission applies LRU eviction pressure before tripping.

Chaos scenarios are @pytest.mark.chaos(seed=N) — a red run echoes its
seed and replays with ``pytest <nodeid> --chaos-seed=N``.
"""

import numpy as np
import pytest
from test_search_failover import ChaosCluster, _hit_ids, _setup

from elasticsearch_tpu.cluster.data_node import (
    SHARD_FAILED_ACTION,
    SHARD_BULK_REPLICA,
)
from elasticsearch_tpu.cluster.search_action import (
    QUERY_PHASE_ACTION,
    is_retryable_failure,
)
from elasticsearch_tpu.common.errors import (
    CircuitBreakingException,
    EsRejectedExecutionException,
)
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.pressure import IndexingPressure
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops.device import DeviceSegment
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.testing.deterministic import (
    DeterministicTaskQueue,
    DisruptableTransport,
    SimNetwork,
)
from elasticsearch_tpu.testing.faults import MemoryPressureFault
from elasticsearch_tpu.transport.transport import (
    DiscoveryNode,
    ResponseHandler,
)
from elasticsearch_tpu.utils.breaker import (
    CircuitBreaker,
    HierarchyCircuitBreakerService,
)

# ---------------------------------------------------------------------------
# IndexingPressure unit contract
# ---------------------------------------------------------------------------


def test_indexing_pressure_rejects_past_limit_and_releases():
    ip = IndexingPressure(limit_bytes=1000)
    r1 = ip.mark_coordinating_operation_started(600)
    with pytest.raises(EsRejectedExecutionException) as ei:
        ip.mark_primary_operation_started(600)
    assert ei.value.status == 429
    assert ip.rejections("primary") == 1
    # a rejected mark must not leak accounting
    assert ip.current_bytes() == 600
    r1()
    assert ip.current_bytes() == 0
    # release is idempotent
    r1()
    assert ip.current_bytes() == 0


def test_indexing_pressure_replica_headroom():
    """Replica ops get 1.5x headroom — replication is shed LAST."""
    ip = IndexingPressure(limit_bytes=1000)
    r = ip.mark_coordinating_operation_started(900)
    # coordinating/primary budget exhausted...
    with pytest.raises(EsRejectedExecutionException):
        ip.mark_primary_operation_started(200)
    # ...but a replica op still fits under the 1.5x limit
    rr = ip.mark_replica_operation_started(400)
    with pytest.raises(EsRejectedExecutionException):
        ip.mark_replica_operation_started(400)
    assert ip.rejections("replica") == 1
    rr()
    r()
    assert ip.current_bytes() == 0


def test_indexing_pressure_stats_shape():
    ip = IndexingPressure(limit_bytes=5000)
    r = ip.mark_coordinating_operation_started(100)
    s = ip.stats()["memory"]
    assert s["current"]["coordinating_in_bytes"] == 100
    assert s["current"]["all_in_bytes"] == 100
    assert s["current"]["combined_coordinating_and_primary_in_bytes"] == 100
    assert s["total"]["coordinating_in_bytes"] == 100
    assert s["limit_in_bytes"] == 5000
    r()
    s = ip.stats()["memory"]
    assert s["current"]["all_in_bytes"] == 0
    assert s["total"]["coordinating_in_bytes"] == 100   # cumulative
    assert s["total"]["peak_all_in_bytes"] == 100
    for key in ("coordinating_rejections", "primary_rejections",
                "replica_rejections"):
        assert s["total"][key] == 0


# ---------------------------------------------------------------------------
# in_flight_requests at transport receive
# ---------------------------------------------------------------------------


def _sim_pair(seed=1, total_limit=100_000):
    queue = DeterministicTaskQueue(seed=seed)
    network = SimNetwork(queue)
    a = DisruptableTransport(DiscoveryNode(node_id="a", name="a"), network)
    b = DisruptableTransport(DiscoveryNode(node_id="b", name="b"), network)
    svc = HierarchyCircuitBreakerService(total_limit_bytes=total_limit)
    b.breaker_service = svc
    return queue, a, b, svc


def _send(queue, a, b, action, payload, timeout=10.0):
    box = {}
    a.send_request(b.local_node, action, payload,
                   ResponseHandler(lambda r: box.setdefault("resp", r),
                                   lambda e: box.setdefault("exc", e)),
                   timeout=timeout)
    queue.run_for(timeout + 1)
    return box


def test_inflight_breaker_charges_during_handler_and_releases():
    queue, a, b, svc = _sim_pair()
    br = svc.get_breaker(CircuitBreaker.IN_FLIGHT_REQUESTS)
    seen = {}

    def handler(req, channel, src):
        seen["used_during"] = br.used
        channel.send_response({"ok": True})

    b.register_request_handler("test/echo", handler)
    box = _send(queue, a, b, "test/echo", {"payload": "x" * 256})
    assert box.get("resp") == {"ok": True}
    assert seen["used_during"] > 0
    # release-on-completion: zero after the response went out
    assert br.used == 0


@pytest.mark.chaos(seed=5)
def test_inflight_breaker_trip_is_typed_and_retryable(chaos_seed):
    queue, a, b, svc = _sim_pair(seed=chaos_seed, total_limit=10)
    called = {"n": 0}

    def handler(req, channel, src):
        called["n"] += 1
        channel.send_response({"ok": True})

    b.register_request_handler("indices:data/read/x", handler)
    box = _send(queue, a, b, "indices:data/read/x",
                {"payload": "y" * 256})
    assert called["n"] == 0, "handler must be shed BEFORE it runs"
    exc = box["exc"]
    assert is_retryable_failure(exc), \
        "a breaker trip must classify retryable (another copy may fit)"
    assert "circuit_breaking" in str(
        getattr(exc, "remote_type", "")).lower().replace(
            "circuitbreaking", "circuit_breaking")
    assert svc.get_breaker(
        CircuitBreaker.IN_FLIGHT_REQUESTS).trip_count == 1
    assert svc.get_breaker(CircuitBreaker.IN_FLIGHT_REQUESTS).used == 0


def test_exempt_actions_bypass_inflight_breaker():
    queue, a, b, svc = _sim_pair(total_limit=10)
    done = {}

    def handler(req, channel, src):
        done["ran"] = True
        channel.send_response({"ok": True})

    b.register_request_handler("internal:cluster/coordination/x", handler,
                               can_trip_breaker=False)
    box = _send(queue, a, b, "internal:cluster/coordination/x",
                {"payload": "z" * 256})
    assert done.get("ran") and box.get("resp") == {"ok": True}


# ---------------------------------------------------------------------------
# HBM admission: LRU eviction pressure before tripping
# ---------------------------------------------------------------------------

MAPPINGS = {"properties": {"body": {"type": "text"}, "n": {"type": "long"}}}
WORDS = ["alpha", "beta", "gamma", "delta", "fox", "dog"]


def build_segment(n_docs=40, name="seg0", seed=3):
    rng = np.random.default_rng(seed)
    svc = MapperService(mappings=MAPPINGS)
    w = SegmentWriter()
    for i in range(n_docs):
        w.add(svc.parse(str(i), {
            "body": " ".join(rng.choice(WORDS, 6)), "n": int(i)}))
    return w.build(name)


def _hbm_cache(limit_bytes):
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1 << 30,
                                         hbm_limit_bytes=limit_bytes)
    cache = DeviceSegmentCache()
    cache.set_breaker(svc.get_breaker(CircuitBreaker.HBM))
    return cache, svc.get_breaker(CircuitBreaker.HBM)


def test_hbm_admission_evicts_lru_before_tripping():
    segs = [build_segment(40, f"bp{i}", seed=i) for i in range(3)]
    one = DeviceSegment(segs[0]).hbm_bytes()
    # room for ~2.5 segments: the third admission must evict the LRU
    cache, br = _hbm_cache(int(one * 2.5))
    cache.get(segs[0])
    cache.get(segs[1])
    used_two = br.used
    assert used_two > 0
    cache.get(segs[2])
    assert cache.hbm_breaker_evictions == 1
    assert br.trip_count == 0, "eviction satisfied the admission: no trip"
    stats = cache.hbm_stats()
    assert stats["segments"] == 2
    assert br.used <= int(one * 2.5)
    # the LRU victim was segs[0] (oldest untouched)
    assert segs[0].name not in {n for n in cache._cache}


def test_hbm_admission_respects_recency():
    segs = [build_segment(40, f"lru{i}", seed=10 + i) for i in range(3)]
    one = DeviceSegment(segs[0]).hbm_bytes()
    cache, br = _hbm_cache(int(one * 2.5))
    cache.get(segs[0])
    cache.get(segs[1])
    cache.get(segs[0])          # touch: segs[1] is now least-recent
    cache.get(segs[2])
    assert segs[1].name not in cache._cache
    assert segs[0].name in cache._cache


def test_hbm_trips_only_when_eviction_cannot_free_enough():
    seg = build_segment(60, "big0", seed=42)
    one = DeviceSegment(seg).hbm_bytes()
    cache, br = _hbm_cache(one // 2)
    with pytest.raises(CircuitBreakingException):
        cache.get(seg)
    assert br.trip_count == 1
    assert br.used == 0, "failed admission must not leak accounting"
    assert cache.hbm_stats()["segments"] == 0


def test_hbm_filter_mask_admission_accounted_and_released():
    seg = build_segment(40, "fm0", seed=7)
    one = DeviceSegment(seg).hbm_bytes()
    cache, br = _hbm_cache(one + 8192)
    dev = cache.get(seg)
    base = br.used
    dev.filter_mask("body", ("fox",))
    assert br.used == base + dev.n_docs_padded
    # evicting the segment returns EVERYTHING it charged (masks incl.)
    cache.evict([seg.name])
    assert br.used == 0


def test_hbm_filter_mask_trips_when_no_headroom():
    seg = build_segment(40, "fm1", seed=8)
    one = DeviceSegment(seg).hbm_bytes()
    cache, br = _hbm_cache(one + 10)   # segment fits, masks don't
    dev = cache.get(seg)
    with pytest.raises(CircuitBreakingException):
        dev.filter_mask("body", ("fox",))
    # the failed mask is NOT cached, and accounting balances
    assert dev.cache_stats()["filter_mask"]["entries"] == 0
    cache.evict([seg.name])
    assert br.used == 0


# ---------------------------------------------------------------------------
# chaos: breaker trip → failover → partial results
# ---------------------------------------------------------------------------


def _squeeze_breakers(cluster, node_id):
    node = cluster.cluster_nodes[node_id]
    fault = MemoryPressureFault(breaker_service=node.breaker_service,
                                factor=0.0, floor_bytes=0)
    fault.apply()
    return fault


@pytest.mark.chaos(seed=131)
def test_breaker_trip_fails_over_to_other_copy(tmp_path, chaos_seed):
    """Every copy-holding node but one squeezed to zero: searches still
    return the full, identical top-k by failing over to the healthy
    copies (failed == 0, no crash, no hang)."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster, index="logs", shards=2, replicas=1, n=20)
    coord = cluster.coordinator_excluding("dn-0")
    body = {"query": {"match": {"body": "fox"}}, "sort": [{"n": "desc"}],
            "size": 5}
    healthy = cluster.call(coord.search, "logs", body)
    assert healthy["_shards"]["failed"] == 0, f"seed={chaos_seed}"

    _squeeze_breakers(cluster, "dn-0")
    for _ in range(3):
        resp = cluster.call(coord.search, "logs", body, timeout=60)
        assert _hit_ids(resp) == _hit_ids(healthy), \
            f"seed={chaos_seed}: failover changed the top-k"
        assert resp["_shards"]["failed"] == 0, \
            f"seed={chaos_seed}: {resp['_shards']}"


@pytest.mark.chaos(seed=137)
def test_breaker_trip_partial_results_with_typed_failure(tmp_path,
                                                         chaos_seed):
    """The ONLY copy of one shard lives on a squeezed node: the search
    completes as partial results with a typed circuit_breaking_exception
    in _shards.failures — never an exception, never a hang."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, index="b", shards=2, replicas=1, n=12)
    cluster.call(master.create_index, "a",
                 number_of_shards=1, number_of_replicas=0)
    cluster.run_for(30)
    resp = cluster.call(master.bulk, "a",
                        [{"op": "index", "id": f"a-{i}",
                          "source": {"body": "lonely fox", "n": i}}
                         for i in range(3)])
    assert resp["errors"] == [], f"seed={chaos_seed}"
    cluster.call(master.refresh)
    cluster.run_for(5)

    a_node = cluster.primary_node_id("a", 0)
    coord = cluster.coordinator_excluding(a_node)
    _squeeze_breakers(cluster, a_node)

    resp = cluster.call(
        coord.search, "a,b",
        {"query": {"match": {"body": "fox"}}, "sort": [{"n": "desc"}],
         "size": 20, "allow_partial_search_results": True}, timeout=60)
    sec = resp["_shards"]
    assert sec["total"] == 3 and sec["failed"] == 1, \
        f"seed={chaos_seed}: {sec}"
    failure = sec["failures"][0]
    assert failure["index"] == "a", f"seed={chaos_seed}: {failure}"
    assert failure["reason"]["type"] == "circuit_breaking_exception", \
        f"seed={chaos_seed}: {failure}"
    # b answered completely through healthy copies
    assert resp["hits"]["total"]["value"] == 12, f"seed={chaos_seed}"
    assert all(h["_index"] == "b" for h in resp["hits"]["hits"])
    # the squeezed node really tripped (the fault fired)
    squeezed = cluster.cluster_nodes[a_node].breaker_service
    assert squeezed.get_breaker(
        CircuitBreaker.IN_FLIGHT_REQUESTS).trip_count >= 1
    # telemetry counted it (`breaker.tripped{breaker=...}` series)
    metrics = cluster.cluster_nodes[a_node].telemetry.metrics
    assert metrics.get_value("breaker.tripped",
                             breaker="in_flight_requests") >= 1


# ---------------------------------------------------------------------------
# chaos: indexing-pressure 429s — reject, release, retry, recover
# ---------------------------------------------------------------------------


@pytest.mark.chaos(seed=141)
def test_coordinating_rejection_is_retryable_429(tmp_path, chaos_seed):
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, index="w", shards=1, replicas=0, n=4)
    fault = MemoryPressureFault(
        indexing_pressure=master.indexing_pressure, factor=0.0)
    fault.apply()
    items = [{"op": "index", "id": "r-1",
              "source": {"body": "squeezed", "n": 1}}]
    with pytest.raises(EsRejectedExecutionException) as ei:
        cluster.call(master.bulk, "w", items)
    assert ei.value.status == 429, f"seed={chaos_seed}"
    assert master.indexing_pressure.rejections("coordinating") == 1
    # after restore the SAME bulk succeeds (retry-after-release contract)
    fault.restore()
    resp = cluster.call(master.bulk, "w", items)
    assert resp["errors"] == [], f"seed={chaos_seed}: {resp}"
    assert master.indexing_pressure.current_bytes() == 0


@pytest.mark.chaos(seed=149)
def test_primary_rejection_gives_items_429_then_retry_succeeds(
        tmp_path, chaos_seed):
    """Primary-stage rejection: items carry a retryable 429 (typed
    es_rejected_execution_exception), and the same bulk succeeds after
    the pressure releases — with used bytes back to zero everywhere."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, index="p", shards=1, replicas=0, n=4)
    p_node = cluster.primary_node_id("p", 0)
    coord = cluster.coordinator_excluding(p_node)
    fault = MemoryPressureFault(
        indexing_pressure=cluster.cluster_nodes[p_node].indexing_pressure,
        factor=0.0)
    fault.apply()

    items = [{"op": "index", "id": "p-9",
              "source": {"body": "pressured fox", "n": 9}}]
    resp = cluster.call(coord.bulk, "p", items)
    assert resp["errors"], f"seed={chaos_seed}: expected a 429 bulk"
    item = resp["items"][0]
    assert item["status"] == 429, f"seed={chaos_seed}: {item}"
    assert item["error"]["type"] == "es_rejected_execution_exception", \
        f"seed={chaos_seed}: {item}"
    assert cluster.cluster_nodes[p_node].indexing_pressure.rejections(
        "primary") >= 1

    fault.restore()
    resp = cluster.call(coord.bulk, "p", items)
    assert resp["errors"] == [], f"seed={chaos_seed}: {resp}"
    cluster.call(master.refresh)
    cluster.run_for(5)
    found = cluster.call(coord.search, "p",
                         {"query": {"match": {"body": "pressured"}}})
    assert found["hits"]["total"]["value"] == 1, f"seed={chaos_seed}"
    # release-on-completion invariant, cluster-wide
    for cn in cluster.cluster_nodes.values():
        assert cn.indexing_pressure.current_bytes() == 0, \
            f"seed={chaos_seed}: leaked in-flight bytes on " \
            f"{cn.local_node.name}"


@pytest.mark.chaos(seed=151)
def test_replica_429_retries_and_never_marks_stale(tmp_path, chaos_seed):
    """An overloaded replica rejecting bulks is retried with backoff by
    the primary and must NEVER reach the master as shard-failed; once
    pressure releases the replica catches up."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = cluster.stabilise()
    cluster.call(master.create_index, "r",
                 number_of_shards=1, number_of_replicas=1)
    cluster.run_for(60)
    p_node = cluster.primary_node_id("r", 0)
    r_node = next(iter(cluster.shard_node_ids("r", 0) - {p_node}))
    replica_cn = cluster.cluster_nodes[r_node]
    # make sure the PRIMARY's applied state has the replica started
    # BEFORE any write, so every op below replicates and checkpoints
    # stay aligned. A node that missed the publication now catches up on
    # its own: the follower check carries the leader's applied version
    # and a lagging node requests a resend (coordination.py
    # RESEND_STATE_ACTION) — no no-op-index-create nudge needed.
    primary_dn = cluster.cluster_nodes[p_node].data_node

    def replication_targets():
        return primary_dn._replication_targets(
            "r", 0, primary_dn.shards[("r", 0)])

    for _ in range(5):
        if replication_targets():
            break
        cluster.run_for(30)
    assert replication_targets(), \
        f"seed={chaos_seed}: primary never saw the started replica"
    resp = cluster.call(master.bulk, "r",
                        [{"op": "index", "id": f"doc-{i}",
                          "source": {"body": "seed fox", "n": i}}
                         for i in range(4)])
    assert resp["errors"] == [], f"seed={chaos_seed}: {resp}"
    cluster.run_for(5)
    fault = MemoryPressureFault(
        indexing_pressure=replica_cn.indexing_pressure, factor=0.0)
    fault.apply()
    # pressure drains mid-flight (virtual time), while the primary is
    # still backing off — the retry then succeeds
    cluster.queue.schedule(3.0, fault.restore, "restore-pressure")

    shard_failed_before = cluster.injector.send_count(SHARD_FAILED_ACTION)
    replica_sends_before = cluster.injector.send_count(SHARD_BULK_REPLICA)
    # coordinate from a node whose own (coordinating-stage) pressure is
    # NOT squeezed — only the replica stage on r_node is under pressure
    coord = cluster.coordinator_excluding(r_node)
    resp = cluster.call(
        coord.bulk, "r",
        [{"op": "index", "id": "r-9",
          "source": {"body": "late replica", "n": 9}}], timeout=90)
    assert resp["errors"] == [], f"seed={chaos_seed}: {resp}"
    # the replica rejected at least once, the primary retried
    assert replica_cn.indexing_pressure.rejections("replica") >= 1, \
        f"seed={chaos_seed}: fault never fired"
    assert cluster.injector.send_count(SHARD_BULK_REPLICA) \
        > replica_sends_before + 1, f"seed={chaos_seed}: no retry sent"
    # NEVER a shard-failed master action for backpressure
    assert cluster.injector.send_count(SHARD_FAILED_ACTION) == \
        shard_failed_before, \
        f"seed={chaos_seed}: backpressure marked the replica stale"
    # the replica caught up once pressure released
    cluster.run_for(10)
    p_shard = cluster.cluster_nodes[p_node].data_node.shards[("r", 0)]
    r_shard = replica_cn.data_node.shards[("r", 0)]
    assert r_shard.engine.tracker.checkpoint == \
        p_shard.engine.tracker.max_seq_no, f"seed={chaos_seed}"
    for cn in cluster.cluster_nodes.values():
        assert cn.indexing_pressure.current_bytes() == 0


@pytest.mark.chaos(seed=157)
def test_memory_pressure_fault_shrinks_limits_mid_flight(tmp_path,
                                                         chaos_seed):
    """The seeded memory-pressure fault lands at a scheduled virtual
    time: searches before it are whole, searches under it complete as
    partial results (or fail over), and after restore the node serves
    normally again — no crash, no hang, replayable from the seed."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, index="mid", shards=2, replicas=0, n=16)
    some_node = cluster.primary_node_id("mid", 0)
    coord = cluster.coordinator_excluding(some_node)
    node = cluster.cluster_nodes[some_node]
    fault = MemoryPressureFault(breaker_service=node.breaker_service,
                                factor=0.0)
    fault.schedule(cluster.queue, delay=5.0, restore_after=10.0)

    body = {"query": {"match": {"body": "fox"}},
            "allow_partial_search_results": True, "size": 16}
    before = cluster.call(coord.search, "mid", body)
    assert before["_shards"]["failed"] == 0, f"seed={chaos_seed}"
    cluster.run_for(6.0)          # the squeeze has landed
    during = cluster.call(coord.search, "mid", body, timeout=60)
    assert during["_shards"]["failed"] == 1, \
        f"seed={chaos_seed}: {during['_shards']}"
    assert during["_shards"]["failures"][0]["reason"]["type"] == \
        "circuit_breaking_exception", f"seed={chaos_seed}"
    cluster.run_for(10.0)         # restore has landed
    after = cluster.call(coord.search, "mid", body)
    assert after["_shards"]["failed"] == 0, f"seed={chaos_seed}"
    assert _hit_ids(after) == _hit_ids(before), f"seed={chaos_seed}"


@pytest.mark.chaos(seed=163)
def test_same_seed_same_backpressure_same_outcome(tmp_path, chaos_seed):
    """Replayability: the breaker-squeeze schedule and the resulting
    response are a pure function of the seed."""
    def run(path):
        cluster = ChaosCluster(3, path, seed=chaos_seed)
        master = _setup(cluster, index="rp", shards=2, replicas=1, n=10)
        node_id = cluster.primary_node_id("rp", 0)
        _squeeze_breakers(cluster, node_id)
        coord = cluster.coordinator_excluding(node_id)
        resp = cluster.call(
            coord.search, "rp",
            {"query": {"match": {"body": "fox"}},
             "sort": [{"n": "desc"}], "size": 10}, timeout=60)
        trips = cluster.cluster_nodes[node_id].breaker_service \
            .get_breaker(CircuitBreaker.IN_FLIGHT_REQUESTS).trip_count
        return (_hit_ids(resp), resp["_shards"]["failed"], trips)

    out_a = run(tmp_path / "a")
    out_b = run(tmp_path / "b")
    assert out_a == out_b, f"seed={chaos_seed}: {out_a} != {out_b}"


def test_set_breaker_after_warmup_charges_residents_fully():
    """Wiring the hbm breaker AFTER warm-up (masks already built) must
    charge each resident segment's FULL hbm bytes — masks included —
    and balance back to zero on eviction."""
    seg = build_segment(40, "warm0", seed=9)
    cache = DeviceSegmentCache()
    dev = cache.get(seg)                 # built unwired
    dev.filter_mask("body", ("fox",))    # mask built before wiring
    svc = HierarchyCircuitBreakerService(total_limit_bytes=1 << 30,
                                         hbm_limit_bytes=1 << 30)
    br = svc.get_breaker(CircuitBreaker.HBM)
    cache.set_breaker(br)
    assert br.used == dev.hbm_bytes()
    # post-wiring mask builds/evictions stay balanced on top
    dev.filter_mask("body", ("dog",))
    assert br.used == dev.hbm_bytes()
    cache.evict([seg.name])
    assert br.used == 0
