"""Mesh-sharded REST search: a multi-shard index on an 8-device CPU mesh
answers `_search` through ONE shard_map program, with results identical to
the per-shard loop and (under matched statistics) to a 1-shard layout.

VERDICT round-1 item 2: index docs over REST, get identical results from
1-shard and 8-shard layouts."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

VOCAB = ["amber", "basalt", "cedar", "dune", "ember", "fjord", "granite",
         "harbor", "islet", "juniper", "krill", "lagoon", "mesa", "nectar"]


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def seed(node, index, n_shards, n_docs=120):
    rng = np.random.default_rng(5)
    do(node, "PUT", f"/{index}", body={
        "settings": {"index": {"number_of_shards": n_shards}},
        "mappings": {"properties": {"title": {"type": "text"},
                                    "tag": {"type": "keyword"},
                                    "views": {"type": "long"}}}})
    for i in range(n_docs):
        do(node, "PUT", f"/{index}/_doc/{i}",
           body={"title": " ".join(rng.choice(VOCAB, rng.integers(2, 10))),
                 "tag": str(rng.choice(["x", "y"])),
                 "views": int(rng.integers(0, 50))}, expect=201)
    do(node, "POST", f"/{index}/_refresh")
    # one segment per shard — the mesh residency requirement
    do(node, "POST", f"/{index}/_forcemerge")


QUERIES = [
    {"match": {"title": "amber dune"}},
    {"match": {"title": {"query": "cedar fjord mesa",
                         "operator": "and"}}},
    {"bool": {"must": [{"match": {"title": "granite"}}],
              "filter": [{"term": {"tag": "x"}}]}},
    {"bool": {"should": [{"match": {"title": "krill"}},
                         {"match": {"title": "lagoon harbor"}}],
              "minimum_should_match": 1}},
    {"multi_match": {"query": "ember islet", "fields": ["title"]}},
]


def search(node, index, body, size=200):
    return do(node, "POST", f"/{index}/_search",
              body={"query": body, "size": size})


def test_mesh_equals_per_shard_loop(node):
    """The SPMD program and the per-shard loop return identical hits."""
    seed(node, "m8", n_shards=8)
    svc = node.search_service
    for q in QUERIES:
        before = svc.mesh_executor.mesh_searches
        r_mesh = search(node, "m8", q)
        assert svc.mesh_executor.mesh_searches == before + 1, q
        # force the per-shard loop by disabling the executor
        ex, svc.mesh_executor = svc.mesh_executor, _Disabled()
        try:
            r_loop = search(node, "m8", q)
        finally:
            svc.mesh_executor = ex
        mesh_hits = [(h["_id"], round(h["_score"], 4))
                     for h in r_mesh["hits"]["hits"]]
        loop_hits = [(h["_id"], round(h["_score"], 4))
                     for h in r_loop["hits"]["hits"]]
        assert mesh_hits == loop_hits, q
        assert r_mesh["hits"]["total"]["value"] == \
            r_loop["hits"]["total"]["value"], q


class _Disabled:
    mesh_searches = 0

    def execute(self, *a, **kw):
        return None


def test_one_shard_vs_eight_shards(node):
    """Same corpus, 1-shard and 8-shard layouts: identical doc sets and
    totals; identical order under dfs_query_then_fetch-style matched
    statistics (per-shard IDF legitimately differs between layouts, as in
    the reference — so default ordering is compared as sets + totals)."""
    seed(node, "one", n_shards=1)
    seed(node, "eight", n_shards=8)
    for q in QUERIES:
        r1 = search(node, "one", q)
        r8 = search(node, "eight", q)
        ids1 = {h["_id"] for h in r1["hits"]["hits"]}
        ids8 = {h["_id"] for h in r8["hits"]["hits"]}
        assert ids1 == ids8, q
        assert (r1["hits"]["total"]["value"]
                == r8["hits"]["total"]["value"]), q


def test_mesh_skips_incompatible(node):
    """Aggs / sorts / scripts take the per-shard path untouched."""
    seed(node, "mx", n_shards=4, n_docs=40)
    svc = node.search_service
    before = svc.mesh_executor.mesh_searches
    r = do(node, "POST", "/mx/_search", body={
        "query": {"match": {"title": "amber"}},
        "aggs": {"tags": {"terms": {"field": "tag"}}},
    })
    assert "tags" in r["aggregations"]
    r = do(node, "POST", "/mx/_search", body={
        "query": {"match": {"title": "amber"}},
        "sort": [{"views": "desc"}],
    })
    assert svc.mesh_executor.mesh_searches == before


def test_mesh_missing_terms(node):
    seed(node, "mz", n_shards=4, n_docs=30)
    r = search(node, "mz", {"match": {"title": "zzznope"}})
    assert r["hits"]["hits"] == []
    assert r["hits"]["total"]["value"] == 0


def test_mesh_multi_segment_shards(node, monkeypatch):
    """Shards with MULTIPLE segments (no force merge): the DEFAULT
    serving contract is byte-identical results, and composite residency
    concatenates a shard's segments into one kernel array whose
    segmented sums round with a different cumsum prefix base than the
    per-segment loop — so unmerged shards take the per-shard loop with
    a typed ``fallback.multi_segment`` counter. ESTPU_MESH_COMPOSITE=1
    opts into the approximate composite mode (VERDICT r2 item 7), whose
    results match the loop to float32 tolerance and whose hits resolve
    to the right segment-local docs."""
    rng = np.random.default_rng(9)
    do(node, "PUT", "/ms", body={
        "settings": {"index": {"number_of_shards": 4}},
        "mappings": {"properties": {"title": {"type": "text"},
                                    "views": {"type": "long"}}}})
    # three refresh generations → multiple segments per shard
    did = 0
    for _gen in range(3):
        for _ in range(40):
            do(node, "PUT", f"/ms/_doc/{did}",
               body={"title": " ".join(rng.choice(
                   VOCAB, rng.integers(2, 10))),
                   "views": did}, expect=201)
            did += 1
        do(node, "POST", "/ms/_refresh")
    svc = node.search_service
    searchers = node.indices_service.get("ms").shard_searchers()
    assert any(len(s.segments) > 1 for s in searchers), \
        "fixture must produce multi-segment shards"
    # default: clean typed fallback, results come from the loop
    before = svc.mesh_executor.mesh_searches
    fb = svc.mesh_executor.counters.get("fallback.multi_segment", 0)
    r = search(node, "ms", QUERIES[0])
    assert svc.mesh_executor.mesh_searches == before
    assert svc.mesh_executor.counters["fallback.multi_segment"] == fb + 1
    assert r["hits"]["hits"], "loop fallback must still answer"
    # opt-in composite mode: mesh serves, results match to f32 tolerance
    monkeypatch.setenv("ESTPU_MESH_COMPOSITE", "1")
    for q in QUERIES[:2] + [QUERIES[3]]:
        before = svc.mesh_executor.mesh_searches
        r_mesh = search(node, "ms", q)
        assert svc.mesh_executor.mesh_searches == before + 1, q
        ex, svc.mesh_executor = svc.mesh_executor, _Disabled()
        try:
            r_loop = search(node, "ms", q)
        finally:
            svc.mesh_executor = ex
        # composite residency sums a doc's contributions on a different
        # cumsum prefix base than the per-segment loop, so scores drift
        # in the last f32 bits and exact-tied ranks may swap — compare
        # id sets and rank-wise scores to tolerance, totals exactly
        assert ({h["_id"] for h in r_mesh["hits"]["hits"]}
                == {h["_id"] for h in r_loop["hits"]["hits"]}), q
        mesh_scores = sorted(h["_score"] for h in r_mesh["hits"]["hits"])
        loop_scores = sorted(h["_score"] for h in r_loop["hits"]["hits"])
        assert np.allclose(mesh_scores, loop_scores, atol=1e-3), q
        assert r_mesh["hits"]["total"] == r_loop["hits"]["total"], q
        # fetch resolves composite docids to the right segment-local doc
        for h in r_mesh["hits"]["hits"]:
            assert h["_source"]["views"] == int(h["_id"])


def test_mesh_float_pack_overflow_falls_back(node, monkeypatch):
    """Global ids past the float32-exact ceiling (n_shards * nd_padded
    >= 2^24) must SKIP the mesh fast path — the packed readback would
    silently corrupt low docid bits — and serve through the per-shard
    loop instead."""
    import elasticsearch_tpu.ops.plan as plan_mod
    seed(node, "ovf", n_shards=4, n_docs=40)
    svc = node.search_service
    # trip ONLY the mesh-level guard (n_shards * nd_padded vs the
    # limit); per-segment builds stay legal — their nd is fine
    monkeypatch.setattr(plan_mod, "PACKED_ID_LIMIT", 1)
    monkeypatch.setattr(plan_mod, "check_packed_id_limit",
                        lambda nd, where: None)
    before = svc.mesh_executor.mesh_searches
    r = search(node, "ovf", {"match": {"title": "amber"}})
    assert svc.mesh_executor.mesh_searches == before, \
        "overflow-sized layout must not take the mesh path"
    # the per-shard fallback still answers correctly
    assert r["hits"]["total"]["value"] > 0
    for h in r["hits"]["hits"]:
        assert "amber" in h["_source"]["title"]
