"""Persistent-task framework tests (model: the reference's
PersistentTasksClusterService/NodeService tests: assignment, state
checkpointing, restart recovery, cancellation)."""

import tempfile

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.transport.persistent import PersistentTasksService


class RecordingExecutor:
    """Poll-driven executor: records started tasks; tests drive progress."""

    def __init__(self):
        self.started = []

    def __call__(self, task):
        self.started.append(task)
        return self


def test_start_checkpoint_complete():
    svc = PersistentTasksService()
    ex = RecordingExecutor()
    svc.register_executor("test/counter", ex)
    tid = svc.start_task("test/counter", {"target": 3})
    assert len(ex.started) == 1
    task = ex.started[0]
    assert task.params == {"target": 3}
    task.update_state({"count": 2})
    assert svc.get(tid)["state"] == {"count": 2}
    task.complete()
    assert svc.get(tid)["finished"] is True


def test_unknown_task_name_rejected():
    svc = PersistentTasksService()
    with pytest.raises(IllegalArgumentException):
        svc.start_task("nope", {})


def test_restart_reassigns_unfinished_tasks():
    path = tempfile.mkdtemp()
    svc1 = PersistentTasksService(path)
    ex1 = RecordingExecutor()
    svc1.register_executor("test/follow", ex1)
    tid = svc1.start_task("test/follow", {"leader": "l1"})
    ex1.started[0].update_state({"checkpoint": 42})

    # simulate restart: new service over the same data path
    svc2 = PersistentTasksService(path)
    ex2 = RecordingExecutor()
    svc2.register_executor("test/follow", ex2)
    svc2.reassign()
    assert len(ex2.started) == 1
    resumed = ex2.started[0]
    assert resumed.id == tid
    assert resumed.state == {"checkpoint": 42}   # resumes from checkpoint
    assert resumed.params == {"leader": "l1"}


def test_finished_tasks_not_reassigned():
    path = tempfile.mkdtemp()
    svc1 = PersistentTasksService(path)
    ex1 = RecordingExecutor()
    svc1.register_executor("test/x", ex1)
    svc1.start_task("test/x", {})
    ex1.started[0].complete()

    svc2 = PersistentTasksService(path)
    ex2 = RecordingExecutor()
    svc2.register_executor("test/x", ex2)
    svc2.reassign()
    assert ex2.started == []


def test_cancel_sets_cancelled_and_removes():
    svc = PersistentTasksService()
    ex = RecordingExecutor()
    svc.register_executor("test/y", ex)
    tid = svc.start_task("test/y", {})
    task = ex.started[0]
    svc.cancel_task(tid)
    assert task.is_cancelled()
    with pytest.raises(ResourceNotFoundException):
        svc.get(tid)


def test_fail_records_reason():
    svc = PersistentTasksService()
    ex = RecordingExecutor()
    svc.register_executor("test/z", ex)
    tid = svc.start_task("test/z", {})
    ex.started[0].fail("boom")
    row = svc.get(tid)
    assert row["finished"] and row["failure"] == "boom"


def test_list_filters_by_name():
    svc = PersistentTasksService()
    ex = RecordingExecutor()
    svc.register_executor("a", ex)
    svc.register_executor("b", ex)
    svc.start_task("a", {})
    svc.start_task("b", {})
    assert len(svc.list()) == 2
    assert len(svc.list("a")) == 1
