"""Cluster coordination: CoordinationState safety unit tests +
deterministic multi-node simulation with disruptions (ref strategy:
CoordinationStateTests + AbstractCoordinatorTestCase.runRandomly/
stabilise over DisruptableMockTransport, SURVEY.md §4.3)."""

import pytest

from elasticsearch_tpu.cluster.coordination import (
    MODE_CANDIDATE,
    MODE_FOLLOWER,
    MODE_LEADER,
    CoordinationState,
    CoordinationStateRejectedException,
    Coordinator,
    Join,
    PersistedState,
)
from elasticsearch_tpu.cluster.state import (
    ClusterState,
    CoordinationMetadata,
    DiscoveryNodes,
    Metadata,
    VotingConfiguration,
)
from elasticsearch_tpu.testing.deterministic import (
    BLACKHOLE,
    DISCONNECTED,
    DeterministicTaskQueue,
    DisruptableTransport,
    SimNetwork,
)
from elasticsearch_tpu.transport.transport import DiscoveryNode


def make_node(i):
    return DiscoveryNode(node_id=f"node-{i}", name=f"n{i}")


def bootstrapped_state(nodes, config_ids):
    config = VotingConfiguration(frozenset(config_ids))
    return ClusterState(
        version=0, term=0, state_uuid="init",
        nodes=DiscoveryNodes(tuple(nodes)),
        metadata=Metadata(coordination=CoordinationMetadata(
            term=0, last_committed_config=config,
            last_accepted_config=config)))


# ------------------------------------------------ CoordinationState unit

class TestCoordinationState:
    def setup_method(self):
        self.n = [make_node(i) for i in range(3)]
        init = bootstrapped_state(self.n, [n.node_id for n in self.n])
        self.states = {
            n.node_id: CoordinationState(n, PersistedState(0, init))
            for n in self.n}

    def test_start_join_bumps_term_once(self):
        s = self.states["node-0"]
        join = s.handle_start_join(self.n[0], 1)
        assert s.current_term() == 1
        assert join.term == 1
        with pytest.raises(CoordinationStateRejectedException):
            s.handle_start_join(self.n[0], 1)  # same term again

    def test_election_needs_quorum(self):
        s0 = self.states["node-0"]
        j0 = s0.handle_start_join(self.n[0], 1)
        assert s0.handle_join(j0) is False  # 1/3 votes
        assert not s0.election_won
        j1 = self.states["node-1"].handle_start_join(self.n[0], 1)
        assert s0.handle_join(j1) is True   # 2/3 → won
        assert s0.election_won

    def test_join_with_newer_accepted_state_rejected(self):
        # node-1 accepts a state at (term 1, v 5); node-0 stays at v0.
        s1 = self.states["node-1"]
        s1.handle_start_join(self.n[1], 1)
        newer = bootstrapped_state(
            self.n, [n.node_id for n in self.n]).with_(term=1, version=5)
        s1.handle_publish_request(newer)
        # new election at term 2: node-1's join reports (1, 5)
        s0 = self.states["node-0"]
        s0.handle_start_join(self.n[0], 2)
        j1 = s1.handle_start_join(self.n[0], 2)
        assert (j1.last_accepted_term, j1.last_accepted_version) == (1, 5)
        with pytest.raises(CoordinationStateRejectedException,
                           match="newer"):
            s0.handle_join(j1)

    def _elect(self, s, term):
        for nid in list(self.states):
            node = next(n for n in self.n if n.node_id == nid)
            j = self.states[nid].handle_start_join(s.local_node, term) \
                if nid != s.local_node.node_id else \
                s.handle_start_join(s.local_node, term)
            try:
                s.handle_join(j)
            except CoordinationStateRejectedException:
                pass
        assert s.election_won

    def test_publish_commit_roundtrip(self):
        s0 = self.states["node-0"]
        self._elect(s0, 1)
        new = s0.last_accepted_state().with_(term=1, version=1,
                                             state_uuid="v1")
        s0.handle_client_value(new)
        # self-accept + one other accept → quorum
        r0 = s0.handle_publish_request(new)
        assert s0.handle_publish_response("node-0", **{
            "term": r0["term"], "version": r0["version"]}) is False
        r1 = self.states["node-1"].handle_publish_request(new)
        assert s0.handle_publish_response("node-1", r1["term"],
                                          r1["version"]) is True
        committed = self.states["node-1"].handle_commit(1, 1)
        assert committed.version == 1

    def test_commit_of_wrong_version_rejected(self):
        s0 = self.states["node-0"]
        self._elect(s0, 1)
        new = s0.last_accepted_state().with_(term=1, version=1,
                                             state_uuid="v1")
        s0.handle_client_value(new)
        s0.handle_publish_request(new)
        with pytest.raises(CoordinationStateRejectedException):
            s0.handle_commit(1, 2)

    def test_stale_term_publish_rejected(self):
        s1 = self.states["node-1"]
        s1.handle_start_join(self.n[1], 5)
        stale = s1.last_accepted_state().with_(term=3, version=1)
        with pytest.raises(CoordinationStateRejectedException):
            s1.handle_publish_request(stale)

    def test_cannot_publish_without_election(self):
        s0 = self.states["node-0"]
        s0.handle_start_join(self.n[0], 1)
        new = s0.last_accepted_state().with_(term=1, version=1)
        with pytest.raises(CoordinationStateRejectedException):
            s0.handle_client_value(new)


# ----------------------------------------------------- simulated cluster

class SimCluster:
    """N coordinators over a deterministic network (the
    AbstractCoordinatorTestCase.Cluster analogue)."""

    def __init__(self, n_nodes, seed=0):
        self.queue = DeterministicTaskQueue(seed=seed)
        self.network = SimNetwork(self.queue)
        self.nodes = [make_node(i) for i in range(n_nodes)]
        self.coordinators = {}
        self.applied = {}
        names = [n.name for n in self.nodes]
        for node in self.nodes:
            transport = DisruptableTransport(node, self.network)
            apply_log = []
            self.applied[node.node_id] = apply_log
            coord = Coordinator(
                transport, self.queue,
                seed_nodes=self.nodes,
                initial_master_nodes=names,
                on_committed_state=(
                    lambda s, log=apply_log: log.append(s)),
                rng=self.queue.random)
            self.coordinators[node.node_id] = coord
        for c in self.coordinators.values():
            c.start()

    def run_for(self, seconds):
        self.queue.run_for(seconds)

    def leaders(self):
        return [c for c in self.coordinators.values()
                if c.mode == MODE_LEADER]

    def stabilise(self, seconds=60):
        self.run_for(seconds)
        leaders = self.leaders()
        assert len(leaders) == 1, \
            f"expected one leader, got {[c.local_node.name for c in leaders]}"
        return leaders[0]

    def coordinator(self, node):
        return self.coordinators[node.node_id]


def test_single_node_cluster_elects_itself():
    cluster = SimCluster(1, seed=42)
    leader = cluster.stabilise(30)
    assert leader.applied_state.nodes.master_node_id == \
        leader.local_node.node_id


@pytest.mark.parametrize("seed", [0, 1, 7])
def test_three_node_cluster_elects_leader_and_converges(seed):
    cluster = SimCluster(3, seed=seed)
    leader = cluster.stabilise()
    # all nodes joined the cluster state and agree on the master
    state = leader.applied_state
    assert state.nodes.size == 3
    for c in cluster.coordinators.values():
        assert c.applied_state.nodes.master_node_id == \
            leader.local_node.node_id
        assert c.applied_state.version == state.version
        assert c.mode in (MODE_LEADER, MODE_FOLLOWER)


def test_publication_reaches_all_nodes():
    cluster = SimCluster(3, seed=3)
    leader = cluster.stabilise()
    results = []
    leader.submit_state_update(
        "test-update",
        lambda s: s.with_(metadata=s.metadata.with_index(
            __import__("elasticsearch_tpu.cluster.state",
                       fromlist=["IndexMetadata"]).IndexMetadata(
                index="idx", uuid="u1", number_of_shards=2))),
        on_done=results.append)
    cluster.run_for(10)
    assert results == [None]
    for c in cluster.coordinators.values():
        assert c.applied_state.metadata.index("idx") is not None


def test_leader_isolation_triggers_failover_and_step_down():
    cluster = SimCluster(3, seed=11)
    leader = cluster.stabilise()
    others = [n for n in cluster.nodes
              if n.node_id != leader.local_node.node_id]
    # blackhole the leader from the rest
    cluster.network.isolate(leader.local_node, cluster.nodes,
                            mode=BLACKHOLE)
    cluster.run_for(120)
    new_leaders = [c for c in cluster.leaders()
                   if c.local_node.node_id != leader.local_node.node_id]
    assert len(new_leaders) == 1, "majority side must elect a new leader"
    new_leader = new_leaders[0]
    # old leader must have stepped down (lost its followers)
    assert leader.mode != MODE_LEADER
    # majority-side nodes agree
    for n in others:
        c = cluster.coordinator(n)
        assert c.applied_state.nodes.master_node_id == \
            new_leader.local_node.node_id
    # heal: old leader rejoins as follower
    cluster.network.heal()
    cluster.run_for(60)
    assert leader.mode == MODE_FOLLOWER
    assert leader.applied_state.nodes.master_node_id == \
        new_leader.local_node.node_id


def test_minority_partition_cannot_elect():
    cluster = SimCluster(5, seed=5)
    leader = cluster.stabilise()
    # partition 2 nodes (minority) away, including the leader
    minority = [leader.local_node]
    for n in cluster.nodes:
        if n.node_id != leader.local_node.node_id:
            minority.append(n)
            break
    majority = [n for n in cluster.nodes if n not in minority]
    cluster.network.partition(minority, majority, mode=DISCONNECTED)
    cluster.run_for(120)
    minority_leaders = [c for c in cluster.leaders()
                        if c.local_node in minority]
    majority_leaders = [c for c in cluster.leaders()
                        if c.local_node in majority]
    assert len(majority_leaders) == 1
    assert minority_leaders == []


def test_node_disconnect_removed_from_cluster_and_rejoins():
    cluster = SimCluster(3, seed=9)
    leader = cluster.stabilise()
    victim = next(n for n in cluster.nodes
                  if n.node_id != leader.local_node.node_id)
    cluster.network.isolate(victim, cluster.nodes, mode=DISCONNECTED)
    cluster.run_for(60)
    assert victim.node_id not in leader.applied_state.nodes
    # still a working cluster of 2
    assert len(cluster.leaders()) == 1
    # heal: the removed node must rejoin even though the leader's term
    # never changed (equal-term membership join path)
    cluster.network.heal()
    cluster.run_for(60)
    assert victim.node_id in leader.applied_state.nodes
    assert cluster.coordinator(victim).mode == MODE_FOLLOWER


def test_run_randomly_then_stabilise():
    """The reference's runRandomly(): random disruptions + heals, then
    stabilise and assert convergence (safety under chaos)."""
    cluster = SimCluster(3, seed=13)
    cluster.run_for(20)
    rng = cluster.queue.random
    for _ in range(6):
        a = rng.choice(cluster.nodes)
        mode = rng.choice([BLACKHOLE, DISCONNECTED])
        cluster.network.isolate(a, cluster.nodes, mode=mode)
        cluster.run_for(rng.uniform(5, 30))
        cluster.network.heal()
        cluster.run_for(rng.uniform(5, 30))
    cluster.network.heal()
    leader = cluster.stabilise(240)
    state = leader.applied_state
    # convergence: every node that is in the cluster applies the same state
    for c in cluster.coordinators.values():
        if c.local_node.node_id in state.nodes:
            assert c.applied_state.version == state.version, \
                f"{c.local_node.name} at v{c.applied_state.version} != " \
                f"v{state.version}"
            assert c.applied_state.state_uuid == state.state_uuid


@pytest.mark.parametrize("seed", [0, 3])
def test_voting_only_node_never_becomes_master(seed):
    """A voting_only master-eligible node counts toward quorums but never
    wins elections (ref: x-pack voting-only-node)."""
    cluster = SimCluster(3, seed=seed)
    # rebuild node 0 as voting-only BEFORE any election runs
    import dataclasses
    v_node = cluster.nodes[0]
    cluster.coordinators[v_node.node_id].local_node = dataclasses.replace(
        v_node, roles=("master", "voting_only", "data"))
    leader = cluster.stabilise()
    assert not leader.local_node.is_voting_only()
    assert leader.local_node.node_id != v_node.node_id
    # the voting-only node still follows the leader
    assert cluster.coordinators[v_node.node_id].mode == MODE_FOLLOWER
