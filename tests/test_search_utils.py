"""Search utility APIs: _field_caps, _validate/query, _terms_enum,
_resolve/index, PIT, stored scripts, search templates (ref:
action/fieldcaps, modules/lang-mustache, x-pack terms-enum)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.search.template import render_template


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def seed(node):
    for i in range(10):
        s, _ = node.rest_controller.dispatch(
            "PUT", f"/logs/_doc/{i}", None,
            {"msg": f"error in module {i}", "level": "warn" if i % 2 else "info",
             "code": i})
        assert s in (200, 201)
    do(node, "POST", "/logs/_refresh")


# ------------------------------------------------------------- field caps

def test_field_caps(node):
    seed(node)
    do(node, "PUT", "/other", body={"mappings": {"properties": {
        "code": {"type": "keyword"}}}})
    r = do(node, "GET", "/logs/_field_caps", params={"fields": "code,msg"})
    assert r["fields"]["code"]["long"]["aggregatable"] is True
    assert "text" in r["fields"]["msg"]
    # conflicting types across indices list their indices
    r2 = do(node, "GET", "/_field_caps", params={"fields": "code"})
    assert set(r2["fields"]["code"]) == {"long", "keyword"}
    assert r2["fields"]["code"]["long"]["indices"] == ["logs"]


def test_field_caps_wildcard(node):
    seed(node)
    r = do(node, "POST", "/logs/_field_caps", body={"fields": ["c*"]})
    assert "code" in r["fields"]


# ------------------------------------------------------------ validate

def test_validate_query(node):
    seed(node)
    r = do(node, "GET", "/logs/_validate/query",
           body={"query": {"match": {"msg": "error"}}})
    assert r["valid"] is True
    r2 = do(node, "GET", "/logs/_validate/query",
            body={"query": {"no_such_query": {}}})
    assert r2["valid"] is False
    r3 = do(node, "GET", "/logs/_validate/query", params={"explain": "true"},
            body={"query": {"term": {"level": "info"}}})
    assert r3["explanations"][0]["valid"] is True


# ------------------------------------------------------------ terms enum

def test_terms_enum(node):
    seed(node)
    r = do(node, "POST", "/logs/_terms_enum",
           body={"field": "level", "string": "wa"})
    assert r["terms"] == ["warn"]
    r2 = do(node, "POST", "/logs/_terms_enum",
            body={"field": "msg", "string": "err"})
    assert "error" in r2["terms"]
    r3 = do(node, "POST", "/logs/_terms_enum",
            body={"field": "level", "string": "WA", "case_insensitive": True})
    assert r3["terms"] == ["warn"]


# ------------------------------------------------------------ resolve

def test_resolve_index(node):
    seed(node)
    do(node, "POST", "/_aliases", body={"actions": [
        {"add": {"index": "logs", "alias": "logs-alias"}}]})
    r = do(node, "GET", "/_resolve/index/l*")
    assert any(i["name"] == "logs" for i in r["indices"])
    assert any(a["name"] == "logs-alias" for a in r["aliases"])


# ------------------------------------------------------------ PIT

def test_point_in_time(node):
    seed(node)
    r = do(node, "POST", "/logs/_pit", params={"keep_alive": "1m"})
    pit_id = r["id"]
    # docs indexed after the PIT are invisible to it
    node.rest_controller.dispatch("PUT", "/logs/_doc/new", None,
                                  {"msg": "late", "code": 99})
    do(node, "POST", "/logs/_refresh")
    rs = do(node, "POST", "/_search", body={"pit": {"id": pit_id}, "size": 20})
    assert rs["hits"]["total"]["value"] == 10
    rs2 = do(node, "GET", "/logs/_search", body={"size": 20})
    assert rs2["hits"]["total"]["value"] == 11
    rc = do(node, "DELETE", "/_pit", body={"id": pit_id})
    assert rc["succeeded"] is True
    do(node, "POST", "/_search", body={"pit": {"id": pit_id}}, expect=404)


# ------------------------------------------------------- stored scripts

def test_stored_scripts_crud(node):
    do(node, "PUT", "/_scripts/my-tpl", body={"script": {
        "lang": "mustache",
        "source": {"query": {"match": {"msg": "{{q}}"}}}}})
    r = do(node, "GET", "/_scripts/my-tpl")
    assert r["found"] and r["script"]["lang"] == "mustache"
    do(node, "DELETE", "/_scripts/my-tpl")
    do(node, "GET", "/_scripts/my-tpl", expect=404)


# ------------------------------------------------------------ templates

def test_render_template_basics():
    out = render_template({"query": {"match": {"msg": "{{q}}"}},
                           "size": "{{size}}"},
                          {"q": "hello", "size": 5})
    # a quoted placeholder stays a JSON string (the search body parser is
    # lenient about numeric strings, as in the reference)
    assert out == {"query": {"match": {"msg": "hello"}}, "size": "5"}


def test_render_template_tojson_and_sections():
    src = ('{"query": {"terms": {"tag": {{#toJson}}tags{{/toJson}} }},'
           '"size": {{size}}{{^size}}10{{/size}} }')
    out = render_template(src, {"tags": ["a", "b"]})
    assert out["query"]["terms"]["tag"] == ["a", "b"]
    assert out["size"] == 10
    out2 = render_template(src, {"tags": [], "size": 3})
    assert out2["size"] == 3


def test_render_template_string_escaping():
    out = render_template('{"q": "{{text}}"}', {"text": 'say "hi"\n'})
    assert out["q"] == 'say "hi"\n'


def test_render_template_section_iteration():
    src = ('{"filters": [ {{#clauses}}{"term": {"f": "{{.}}"}},{{/clauses}} '
           '{"match_all": {}} ]}')
    out = render_template(src, {"clauses": ["x", "y"]})
    assert out["filters"][0] == {"term": {"f": "x"}}
    assert out["filters"][2] == {"match_all": {}}


def test_search_template_endpoint(node):
    seed(node)
    r = do(node, "POST", "/logs/_search/template", body={
        "source": {"query": {"match": {"level": "{{lvl}}"}}},
        "params": {"lvl": "info"}})
    assert r["hits"]["total"]["value"] == 5
    # stored template by id
    do(node, "PUT", "/_scripts/lvl-tpl", body={"script": {
        "lang": "mustache",
        "source": {"query": {"match": {"level": "{{lvl}}"}}}}})
    r2 = do(node, "POST", "/logs/_search/template",
            body={"id": "lvl-tpl", "params": {"lvl": "warn"}})
    assert r2["hits"]["total"]["value"] == 5
    r3 = do(node, "POST", "/_render/template", body={
        "id": "lvl-tpl", "params": {"lvl": "warn"}})
    assert r3["template_output"]["query"]["match"]["level"] == "warn"


def test_msearch_template(node):
    seed(node)
    r = do(node, "POST", "/_msearch/template", body=[
        {"index": "logs"},
        {"source": {"query": {"match": {"level": "{{l}}"}}},
         "params": {"l": "info"}},
        {"index": "logs"},
        {"source": {"query": {"match_all": {}}}},
    ])
    assert r["responses"][0]["hits"]["total"]["value"] == 5
    assert r["responses"][1]["hits"]["total"]["value"] == 10


def test_termvectors_api(tmp_path):
    from elasticsearch_tpu.node import Node
    n = Node(data_path=str(tmp_path / "tv"))
    n.indices_service.create_index("tv", {}, {
        "properties": {"t": {"type": "text"}, "k": {"type": "keyword"}}})
    idx = n.indices_service.get("tv")
    idx.index_doc("1", {"t": "the quick quick fox", "k": "skip"})
    idx.index_doc("2", {"t": "lazy fox"})
    idx.refresh()
    st, r = n.rest_controller.dispatch(
        "GET", "/tv/_termvectors/1", {"term_statistics": "true"})
    assert st == 200 and r["found"]
    terms = r["term_vectors"]["t"]["terms"]
    assert terms["quick"]["term_freq"] == 2
    assert len(terms["quick"]["tokens"]) == 2
    assert terms["fox"]["doc_freq"] == 2          # both docs have fox
    assert "k" not in r["term_vectors"]            # keyword not vectorized
    # missing doc
    st, r = n.rest_controller.dispatch("GET", "/tv/_termvectors/404", {})
    assert r["found"] is False
    # multi
    st, r = n.rest_controller.dispatch(
        "POST", "/tv/_mtermvectors", {}, {"ids": ["1", "2"]})
    assert [d["found"] for d in r["docs"]] == [True, True]
    n.close()


def test_termvectors_arrays_routing_and_errors(tmp_path):
    from elasticsearch_tpu.node import Node
    n = Node(data_path=str(tmp_path / "tv2"))
    n.indices_service.create_index("tv2", {"index.number_of_shards": 3}, {
        "properties": {"t": {"type": "text"}}})
    idx = n.indices_service.get("tv2")
    idx.index_doc("1", {"t": ["quick fox", "lazy dog"]}, routing="abc")
    idx.refresh()
    # routing-aware lookup
    st, r = n.rest_controller.dispatch(
        "GET", "/tv2/_termvectors/1", {"routing": "abc"})
    assert r["found"], r
    terms = r["term_vectors"]["t"]["terms"]
    # per-value analysis with the multi-value position gap, no list repr
    assert set(terms) == {"quick", "fox", "lazy", "dog"}
    assert terms["lazy"]["tokens"][0]["position"] >= 100
    # per-doc errors don't abort mtermvectors
    st, r = n.rest_controller.dispatch(
        "POST", "/tv2/_mtermvectors", {},
        {"docs": [{"_index": "nope", "_id": "1"},
                  {"_index": "tv2"},
                  {"_index": "tv2", "_id": "1", "routing": "abc"}]})
    assert st == 200
    assert [d["found"] for d in r["docs"]] == [False, False, True]
    n.close()
