"""Distributed aggregations over the multi-node RPC path
(cluster/search_action.py + search/agg_partials.py): a ≥3-node cluster
must return agg results equal to single-node, with incremental partial
reduce (num_reduce_phases), composition with the PR-1 partial-results
protocol under seeded faults, and typed rejection of unsupported agg
types. Chaos tests replay with ``--chaos-seed=N``."""

import numpy as np
import pytest
from test_search_failover import ChaosCluster

from elasticsearch_tpu.cluster.search_action import QUERY_PHASE_ACTION
from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    SearchPhaseExecutionException,
)
from elasticsearch_tpu.index.service import IndicesService
from elasticsearch_tpu.search.service import SearchService
from elasticsearch_tpu.testing.faults import ERROR, FaultInjector, FaultRule

MAPPINGS = {"properties": {
    "category": {"type": "keyword"},
    "price": {"type": "double"},
    "sold_at": {"type": "date"},
}}

AGGS = {
    "cats": {"terms": {"field": "category"},
             "aggs": {"avg_p": {"avg": {"field": "price"}}}},
    "days": {"date_histogram": {"field": "sold_at",
                                "calendar_interval": "day"},
             "aggs": {"rev": {"sum": {"field": "price"}}}},
    "pct": {"percentiles": {"field": "price",
                            "percents": [25.0, 50.0, 95.0]}},
    "comp": {"composite": {"size": 4, "sources": [
        {"cat": {"terms": {"field": "category"}}}]}},
}


def make_docs(seed, n=60):
    rng = np.random.default_rng(seed)
    cats = ["a", "b", "c"]
    return [{"category": cats[int(rng.integers(0, 3))],
             "price": float(rng.integers(1, 100)),
             "sold_at": f"2021-02-{int(rng.integers(1, 20)):02d}"}
            for _ in range(n)]


def setup_cluster(cluster, docs, shards=3, replicas=0):
    master = cluster.stabilise()
    cluster.call(master.create_index, "shop", number_of_shards=shards,
                 number_of_replicas=replicas, mappings=MAPPINGS)
    cluster.run_for(60)
    items = [{"op": "index", "id": f"d{i}", "source": d}
             for i, d in enumerate(docs)]
    resp = cluster.call(master.bulk, "shop", items)
    assert resp["errors"] == [], f"seed={cluster.seed}: {resp}"
    cluster.call(master.refresh)
    cluster.run_for(5)
    return master


def single_node_truth(tmp_path, docs, body):
    indices = IndicesService(str(tmp_path / "truth"))
    idx = indices.create_index("shop", {"index.number_of_shards": 1},
                               MAPPINGS)
    for i, d in enumerate(docs):
        idx.index_doc(f"d{i}", d)
    idx.refresh()
    try:
        return SearchService(indices).search("shop",
                                             body)["aggregations"]
    finally:
        indices.close()


@pytest.mark.chaos(seed=21)
def test_three_node_aggs_equal_single_node(tmp_path, chaos_seed):
    """The acceptance quartet — terms, date_histogram, percentiles,
    composite (sub-aggs included) — on a 3-node / 3-shard cluster,
    equal to the single-node result."""
    docs = make_docs(chaos_seed)
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = setup_cluster(cluster, docs)
    body = {"size": 0, "aggs": AGGS, "batched_reduce_size": 2}
    r = cluster.call(master.search, "shop", body)
    assert r["_shards"]["failed"] == 0, f"seed={chaos_seed}: {r}"
    # the incremental reduce ran: 3 shards at batch size 2 → ≥ 2
    # phases (partial + final)
    assert r["num_reduce_phases"] >= 2, f"seed={chaos_seed}"
    truth = single_node_truth(tmp_path, docs,
                              {"size": 0, "aggs": AGGS})
    a = r["aggregations"]
    assert [(b["key"], b["doc_count"]) for b in a["cats"]["buckets"]] \
        == [(b["key"], b["doc_count"]) for b in truth["cats"]["buckets"]]
    for bd, bt in zip(a["cats"]["buckets"], truth["cats"]["buckets"]):
        assert bd["avg_p"]["value"] == pytest.approx(
            bt["avg_p"]["value"]), f"seed={chaos_seed}"
    assert [(b["key"], b["doc_count"]) for b in a["days"]["buckets"]] \
        == [(b["key"], b["doc_count"]) for b in truth["days"]["buckets"]]
    for bd, bt in zip(a["days"]["buckets"], truth["days"]["buckets"]):
        assert bd["rev"]["value"] == pytest.approx(bt["rev"]["value"])
    # the sample fits the centroid budget → percentiles are EXACT
    assert a["pct"]["values"] == truth["pct"]["values"]
    assert a["comp"] == truth["comp"]
    # no raw-sample carrier leaks into the wire response
    import json
    assert "_values" not in json.dumps(r) \
        and "_digest" not in json.dumps(r)
    # the coordinator surfaced the reduce telemetry
    coord_metrics = master.telemetry.metrics.to_dict()
    assert coord_metrics["search.agg_reduce.partials"]["value"] >= 3
    assert coord_metrics["search.agg_reduce.batches"]["value"] >= 1
    assert any(k.startswith("search.agg_reduce.latency")
               for k in coord_metrics)


@pytest.mark.chaos(seed=33)
def test_aggs_compose_with_partial_results(tmp_path, chaos_seed):
    """PR-1 composition: with no replicas, a node whose query RPC
    always errors yields typed `_shards.failures` — and the
    aggregations reduce over the SURVIVING shards instead of failing
    the request; allow_partial_search_results=false raises instead."""
    docs = make_docs(chaos_seed)
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = setup_cluster(cluster, docs, shards=3, replicas=0)
    healthy = cluster.call(master.search, "shop",
                           {"size": 0, "aggs": AGGS})
    assert healthy["_shards"]["failed"] == 0
    total_docs = sum(b["doc_count"]
                     for b in healthy["aggregations"]["cats"]["buckets"])
    assert total_docs == len(docs)

    victim = cluster.primary_node_id("shop", 0)
    cluster.injector.add_rule(FaultRule(
        action=QUERY_PHASE_ACTION, node=victim, mode=ERROR))
    coord = cluster.coordinator_excluding(victim)
    partial = cluster.call(coord.search, "shop",
                           {"size": 0, "aggs": AGGS})
    sec = partial["_shards"]
    assert sec["failed"] >= 1, f"seed={chaos_seed}: {sec}"
    assert sec["failures"], f"seed={chaos_seed}"
    got = sum(b["doc_count"]
              for b in partial["aggregations"]["cats"]["buckets"])
    # strictly fewer docs than healthy (the failed shards' partials
    # never arrived), but still a well-formed reduce
    assert 0 < got < total_docs, f"seed={chaos_seed}: {got}"
    assert partial["num_reduce_phases"] >= 1

    with pytest.raises(SearchPhaseExecutionException):
        cluster.call(coord.search, "shop",
                     {"size": 0, "aggs": AGGS,
                      "allow_partial_search_results": False})
    # the failed search released every buffered partial's breaker
    # charge (the _complete → consumer.close() seam): no residual
    # request-breaker bytes from agg partials at rest
    assert coord.breaker_service.get_breaker("request").used == 0, \
        f"seed={chaos_seed}"


@pytest.mark.chaos(seed=44)
def test_failover_keeps_aggs_complete(tmp_path, chaos_seed):
    """With replicas, a failed copy fails over — the agg partial comes
    from the surviving copy and the reduce stays COMPLETE."""
    docs = make_docs(chaos_seed)
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = setup_cluster(cluster, docs, shards=2, replicas=1)
    victim = cluster.primary_node_id("shop", 0)
    cluster.injector.add_rule(FaultRule(
        action=QUERY_PHASE_ACTION, node=victim, mode=ERROR))
    coord = cluster.coordinator_excluding(victim)
    r = cluster.call(coord.search, "shop", {"size": 0, "aggs": AGGS})
    assert r["_shards"]["failed"] == 0, f"seed={chaos_seed}: {r}"
    got = sum(b["doc_count"]
              for b in r["aggregations"]["cats"]["buckets"])
    assert got == len(docs), f"seed={chaos_seed}"


@pytest.mark.chaos(seed=55)
def test_batched_reduce_size_drives_phase_count(tmp_path, chaos_seed):
    docs = make_docs(chaos_seed)
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = setup_cluster(cluster, docs, shards=4, replicas=0)
    spec = {"size": 0, "aggs": {"c": {"terms": {"field": "category"}}}}
    one_batch = cluster.call(master.search, "shop",
                             {**spec, "batched_reduce_size": 100})
    # 4 partials under one big batch: remainder reduce + final
    assert one_batch["num_reduce_phases"] == 2, f"seed={chaos_seed}"
    small = cluster.call(master.search, "shop",
                         {**spec, "batched_reduce_size": 2})
    assert small["num_reduce_phases"] > \
        one_batch["num_reduce_phases"], f"seed={chaos_seed}"
    assert small["aggregations"] == one_batch["aggregations"]


@pytest.mark.chaos(seed=66)
def test_unsupported_agg_rejected_typed_before_fanout(tmp_path,
                                                      chaos_seed):
    docs = make_docs(chaos_seed, n=10)
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    master = setup_cluster(cluster, docs)
    with pytest.raises(IllegalArgumentException) as ei:
        cluster.call(master.search, "shop", {
            "size": 0,
            "aggs": {"sig": {"significant_terms": {
                "field": "category"}}}})
    assert "distributed" in str(ei.value)
    # single-node search still serves the same body
    truth = single_node_truth(
        tmp_path, docs,
        {"size": 0, "aggs": {"sig": {"significant_terms": {
            "field": "category", "min_doc_count": 1}}}})
    assert "sig" in truth
