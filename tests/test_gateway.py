"""Incremental persisted cluster state tests (ref:
PersistedClusterStateServiceTests — incremental writes, fsync/commit
discipline, torn-write recovery, generation rotation)."""

import os

import numpy as np
import pytest

from elasticsearch_tpu.cluster.gateway import (
    DurablePersistedState,
    PersistedClusterStateStore,
)
from elasticsearch_tpu.cluster.state import (
    ClusterState,
    IndexMetadata,
    Metadata,
)


def mk_state(version, n_indices=3, fat=0):
    md = Metadata(indices={
        f"idx{i}": IndexMetadata(index=f"idx{i}", uuid=f"u{i}",
                                 settings={"pad": "x" * fat})
        for i in range(n_indices)})
    return ClusterState(version=version, metadata=md)


def log_path(store):
    return store._gen_path(store._gen)


def test_roundtrip_and_restart(tmp_path):
    store = PersistedClusterStateStore(str(tmp_path))
    store.set_current_term(3)
    store.set_last_accepted_state(mk_state(7))
    store.close()

    store2 = PersistedClusterStateStore(str(tmp_path))
    assert store2.current_term() == 3
    st = store2.last_accepted_state()
    assert st.version == 7
    assert set(st.metadata.indices) == {"idx0", "idx1", "idx2"}
    store2.close()


def test_incremental_writes_only_changed_index(tmp_path):
    store = PersistedClusterStateStore(str(tmp_path))
    base = mk_state(1, n_indices=20, fat=2000)   # ~40KB of index docs
    store.set_last_accepted_state(base)
    size_after_full = os.path.getsize(log_path(store))

    # change ONE index's metadata
    md = base.metadata
    changed = dict(md.indices)
    changed["idx0"] = IndexMetadata(index="idx0", uuid="u0",
                                    number_of_replicas=1,
                                    settings={"pad": "y" * 2000})
    st2 = ClusterState(version=2, metadata=Metadata(indices=changed))
    store.set_last_accepted_state(st2)
    delta = os.path.getsize(log_path(store)) - size_after_full
    # one index doc + global doc + commit ≪ the 20-index full write
    assert delta < size_after_full / 3, (delta, size_after_full)
    store.close()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_torn_write_never_loses_committed_state(tmp_path, seed):
    """kill -9 mid-publish: truncate the log at a random point inside
    the LAST publish's bytes; recovery must return the previous
    committed state intact."""
    rng = np.random.default_rng(seed)
    store = PersistedClusterStateStore(str(tmp_path))
    store.set_current_term(1)
    store.set_last_accepted_state(mk_state(5, n_indices=4, fat=300))
    committed_size = os.path.getsize(log_path(store))
    path = log_path(store)

    store.set_last_accepted_state(mk_state(6, n_indices=5, fat=300))
    full_size = os.path.getsize(path)
    store.close()

    cut = int(rng.integers(committed_size + 1, full_size))
    with open(path, "r+b") as f:
        f.truncate(cut)
        # optionally also corrupt the byte before the cut
        if seed % 2 and cut > committed_size + 2:
            f.seek(cut - 1)
            f.write(b"\xff")

    store2 = PersistedClusterStateStore(str(tmp_path))
    st = store2.last_accepted_state()
    assert st is not None and st.version == 5
    assert set(st.metadata.indices) == {f"idx{i}" for i in range(4)}
    assert store2.current_term() == 1
    store2.close()


def test_recover_write_restart_keeps_post_recovery_commits(tmp_path):
    """A torn tail must be TRUNCATED at recovery: states committed after
    the recovery must survive the NEXT restart (appending behind a
    corrupt frame would hide them forever)."""
    store = PersistedClusterStateStore(str(tmp_path))
    store.set_last_accepted_state(mk_state(5))
    committed_size = os.path.getsize(log_path(store))
    path = log_path(store)
    store.set_last_accepted_state(mk_state(6))
    store.close()
    with open(path, "r+b") as f:          # kill -9 mid-publish of v6
        f.truncate(committed_size + 7)

    store2 = PersistedClusterStateStore(str(tmp_path))
    assert store2.last_accepted_state().version == 5
    store2.set_last_accepted_state(mk_state(7))   # durable post-recovery
    store2.close()

    store3 = PersistedClusterStateStore(str(tmp_path))
    assert store3.last_accepted_state().version == 7
    store3.close()


def test_first_publish_torn_then_commits_survive(tmp_path):
    """kill -9 during the VERY FIRST publish (torn frame, no commit
    barrier anywhere): the store must truncate the corrupt tail before
    appending, or every later fsynced commit hides behind the bad frame
    on the next restart — silently losing committed state."""
    store = PersistedClusterStateStore(str(tmp_path))
    store.set_last_accepted_state(mk_state(1))
    path = log_path(store)
    store.close()
    # cut INSIDE the first record and scribble garbage after it so no
    # commit barrier survives and the tail is corrupt
    with open(path, "r+b") as f:
        f.truncate(9)
        f.seek(5)
        f.write(b"\xff\xff\xff\xff")

    store2 = PersistedClusterStateStore(str(tmp_path))
    assert store2.last_accepted_state() is None   # nothing committed
    store2.set_last_accepted_state(mk_state(4))   # new commit, fsynced
    store2.close()

    store3 = PersistedClusterStateStore(str(tmp_path))
    st = store3.last_accepted_state()
    assert st is not None and st.version == 4
    store3.close()


def test_corrupt_crc_rolls_back(tmp_path):
    store = PersistedClusterStateStore(str(tmp_path))
    store.set_last_accepted_state(mk_state(1))
    size1 = os.path.getsize(log_path(store))
    store.set_last_accepted_state(mk_state(2))
    path = log_path(store)
    store.close()
    # flip a byte inside the SECOND publish's frames
    with open(path, "r+b") as f:
        f.seek(size1 + 12)
        b = f.read(1)
        f.seek(size1 + 12)
        f.write(bytes([b[0] ^ 0xFF]))
    store2 = PersistedClusterStateStore(str(tmp_path))
    assert store2.last_accepted_state().version == 1
    store2.close()


def test_rotation_compacts(tmp_path):
    store = PersistedClusterStateStore(str(tmp_path), rotate_bytes=20_000)
    for v in range(1, 30):
        store.set_last_accepted_state(mk_state(v, n_indices=3, fat=500))
    # rotated at least once, only ONE generation remains
    gens = store._generations()
    assert len(gens) == 1 and gens[0] >= 1
    assert os.path.getsize(log_path(store)) < 60_000
    store.close()
    store2 = PersistedClusterStateStore(str(tmp_path))
    assert store2.last_accepted_state().version == 29
    store2.close()


def test_durable_persisted_state_restart(tmp_path):
    d = DurablePersistedState(str(tmp_path))
    d.set_current_term(4)
    d.set_last_accepted_state(mk_state(9))
    d.close()
    d2 = DurablePersistedState(str(tmp_path))
    assert d2.current_term() == 4
    assert d2.last_accepted_state().version == 9
    d2.close()


def test_cluster_node_state_survives_restart(tmp_path):
    """Sim: a 1-node cluster creates an index, the process 'restarts'
    (new ClusterNode over the same data path), and the accepted state —
    term + index metadata — is back (ref: GatewayMetaState recovery)."""
    from elasticsearch_tpu.cluster.node import ClusterNode
    from elasticsearch_tpu.testing.deterministic import (
        DeterministicTaskQueue,
        DisruptableTransport,
        SimNetwork,
    )
    from elasticsearch_tpu.transport.transport import DiscoveryNode

    queue = DeterministicTaskQueue(seed=3)
    network = SimNetwork(queue)
    dn = DiscoveryNode(node_id="g-0", name="g0")
    cn = ClusterNode(DisruptableTransport(dn, network), queue,
                     data_path=str(tmp_path / "g0"),
                     seed_nodes=[dn], initial_master_nodes=["g0"],
                     rng=queue.random)
    cn.start()
    queue.run_for(30)
    assert cn.is_master()
    done = {}
    cn.create_index("survivor", number_of_shards=1, number_of_replicas=0,
                    on_done=lambda r, err=None: done.update(r=r, e=err))
    queue.run_for(30)
    assert done.get("e") is None
    term = cn.coordinator.current_term()
    cn.stop()

    queue2 = DeterministicTaskQueue(seed=4)
    network2 = SimNetwork(queue2)
    cn2 = ClusterNode(DisruptableTransport(dn, network2), queue2,
                      data_path=str(tmp_path / "g0"),
                      seed_nodes=[dn], initial_master_nodes=["g0"],
                      rng=queue2.random)
    restored = cn2.coordinator.coordination_state.last_accepted_state()
    assert "survivor" in restored.metadata.indices
    assert cn2.coordinator.current_term() >= term
    cn2.start()
    queue2.run_for(30)
    assert cn2.is_master()
    assert cn2.coordinator.current_term() > term   # new election, new term
    cn2.stop()
