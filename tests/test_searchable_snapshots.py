"""Searchable snapshots: lazy blob-backed mounts with a local cache
(ref: SearchableSnapshotDirectory / frozen shared cache tests)."""

import glob
import os

import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, expect=200, **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, r
    return r


def _snapshot_index(node, tmp_path):
    call(node, "PUT", "/_snapshot/repo", {
        "type": "fs", "settings": {"location": str(tmp_path / "repo")}})
    call(node, "PUT", "/src", {"mappings": {"properties": {
        "t": {"type": "text"}, "n": {"type": "long"}}}})
    for i in range(30):
        call(node, "PUT", f"/src/_doc/{i}", {"t": f"alpha doc {i}",
                                             "n": i}, expect=201)
    call(node, "POST", "/src/_refresh")
    call(node, "PUT", "/_snapshot/repo/s1", {"indices": "src"},
         wait_for_completion="true")


def test_mount_is_lazy_then_searchable(node, tmp_path):
    _snapshot_index(node, tmp_path)
    call(node, "POST", "/_snapshot/repo/s1/_mount",
         {"index": "src", "renamed_index": "mounted"})

    # NO data files were copied at mount time — only manifests/commits
    shard_dir = os.path.join(node.data_path, "mounted", "0")
    assert os.path.exists(os.path.join(shard_dir, "snapshot_store.json"))
    assert glob.glob(os.path.join(shard_dir, "*", "arrays.npz")) == []

    stats = call(node, "GET", "/_searchable_snapshots/stats")
    assert stats["indices"]["mounted"]["repository"] == "repo"
    misses0 = stats["shared_cache"]["misses"]

    # first search materializes through the cache
    r = call(node, "POST", "/mounted/_search",
             {"query": {"match": {"t": "alpha"}}, "size": 50})
    assert r["hits"]["total"]["value"] == 30
    assert glob.glob(os.path.join(shard_dir, "*", "arrays.npz")) != []
    stats = call(node, "GET", "/_searchable_snapshots/stats")
    assert stats["shared_cache"]["misses"] > misses0
    assert stats["shared_cache"]["bytes_fetched"] > 0

    # mounted indices are read-only
    st, _ = node.rest_controller.dispatch(
        "PUT", "/mounted/_doc/99", None, {"t": "nope"})
    assert st >= 400


def test_mounted_index_survives_restart_lazily(node, tmp_path):
    _snapshot_index(node, tmp_path)
    call(node, "POST", "/_snapshot/repo/s1/_mount",
         {"index": "src", "renamed_index": "m2"})
    data_path = node.data_path
    node.close()

    n2 = Node(data_path=data_path)
    try:
        shard_dir = os.path.join(data_path, "m2", "0")
        # restart reopened the index with segments still deferred
        r = call(n2, "POST", "/m2/_search",
                 {"query": {"match": {"t": "alpha"}}, "size": 50})
        assert r["hits"]["total"]["value"] == 30
        assert glob.glob(os.path.join(shard_dir, "*", "arrays.npz")) != []
    finally:
        n2.close()


def test_flush_before_search_keeps_deferred_segments(node, tmp_path):
    """A flush (or snapshot) of a mounted-but-never-searched index must
    keep deferred segment names in the commit — dropping them would
    silently lose all mounted data on the next open."""
    _snapshot_index(node, tmp_path)
    call(node, "POST", "/_snapshot/repo/s1/_mount",
         {"index": "src", "renamed_index": "mf"})
    call(node, "POST", "/mf/_flush")
    data_path = node.data_path
    node.close()
    n2 = Node(data_path=data_path)
    try:
        r = call(n2, "POST", "/mf/_search",
                 {"query": {"match": {"t": "alpha"}}, "size": 50})
        assert r["hits"]["total"]["value"] == 30
    finally:
        n2.close()


def test_second_mount_hits_cache(node, tmp_path):
    _snapshot_index(node, tmp_path)
    call(node, "POST", "/_snapshot/repo/s1/_mount",
         {"index": "src", "renamed_index": "ma"})
    call(node, "POST", "/ma/_search", {"query": {"match_all": {}}})
    stats1 = call(node, "GET", "/_searchable_snapshots/stats")

    call(node, "POST", "/_snapshot/repo/s1/_mount",
         {"index": "src", "renamed_index": "mb"})
    call(node, "POST", "/mb/_search", {"query": {"match_all": {}}})
    stats2 = call(node, "GET", "/_searchable_snapshots/stats")
    # the same blobs served the second mount from cache
    assert stats2["shared_cache"]["hits"] > stats1["shared_cache"]["hits"]
    assert (stats2["shared_cache"]["misses"]
            == stats1["shared_cache"]["misses"])
