"""Per-request profiling at ES parity (PR-8): ES-shaped per-shard
profile trees with device-kernel attribution, coordinator-merged on the
distributed path, histogram→trace exemplars, hot-threads occupancy,
task profile stages, and the slowlog → `_traces` → profile chain.

Cluster tests ride the seeded chaos harness (test_telemetry.py
ChaosCluster): the profile trees are timed on the SCHEDULER clock, so a
replayed seed produces byte-identical trees — the acceptance invariant.
"""

import copy
import json

import pytest

from elasticsearch_tpu.search import profile
from elasticsearch_tpu.telemetry import context as telectx
from elasticsearch_tpu.telemetry.metrics import MetricsRegistry

from test_telemetry import ChaosCluster, _setup

PROFILE_BODY = {"query": {"match": {"body": "fox"}},
                "profile": True, "size": 5}
AGGS_BODY = {"query": {"match": {"body": "fox"}}, "profile": True,
             "size": 5, "aggs": {"m": {"avg": {"field": "n"}}}}


# ------------------------------------------------------------ single node

@pytest.fixture(scope="module")
def rest_node(tmp_path_factory):
    from elasticsearch_tpu.node import Node
    node = Node(data_path=str(tmp_path_factory.mktemp("profile_node")))
    c = node.rest_controller
    c.dispatch("PUT", "/idx", {}, {"settings": {
        "index.search.slowlog.threshold.query.warn": "0ms"}})
    for i in range(30):
        c.dispatch("PUT", f"/idx/_doc/{i}", {},
                   {"title": f"fox doc {i}", "rank": i})
    c.dispatch("POST", "/idx/_refresh", {}, None)
    yield node
    node.close()


def _search(node, body, params=None):
    status, r = node.rest_controller.dispatch(
        "POST", "/idx/_search", params or {}, body)
    assert status == 200, r
    return r


def test_single_node_profile_shape_and_sum_invariant(rest_node):
    """The ES-shaped tree: shards + coordinator section + trace.id;
    per shard, device+host nanos == total and every breakdown stage is
    bounded by the total."""
    r = _search(rest_node, {"query": {"match": {"title": "fox"}},
                            "profile": True, "size": 5})
    prof = r["profile"]
    assert set(prof) >= {"shards", "coordinator"}
    assert prof["trace.id"] == r["_headers"]["trace.id"]
    assert prof["coordinator"]["phases"]["query_ns"] >= 0
    shard = prof["shards"][0]
    q = shard["searches"][0]["query"][0]
    bd = q["breakdown"]
    total = q["time_in_nanos"]
    assert total > 0
    # the pinned invariant: device + host partition the total exactly,
    # and no stage exceeds it
    assert bd["device_time_in_nanos"] + bd["host_time_in_nanos"] == total
    stages = {k: v for k, v in bd.items()
              if not k.endswith("_time_in_nanos")}
    assert stages and all(0 <= v <= total for v in stages.values())
    assert sum(stages.values()) <= total
    coll = shard["searches"][0]["collector"][0]
    assert coll["name"].endswith("TopDocsCollector")
    assert shard["fetch"]["time_in_nanos"] > 0


def test_device_attribution_on_plan_fastpath(rest_node):
    """A fused-plan (fastpath) query's profile carries the device
    attribution record: kernel name, cohort width, nb bucket, batch
    wait, padding waste, readback bytes — plus the per-kernel
    compile/cache-hit classification from the tracked_jit registry."""
    body = {"query": {"match": {"title": "fox"}}, "profile": True,
            "size": 5, "_source": False}
    _search(rest_node, body)          # warm the shapes
    r = _search(rest_node, body)
    dev = r["profile"]["shards"][0]["device"]
    launch = dev["launches"][0]
    assert launch["kernel"] == "plan_topk_batch"
    assert launch["cohort"] >= 1
    assert launch["q_bucket"] >= launch["cohort"]
    assert launch["nb_bucket"] >= 1
    assert launch["batch_wait_ms"] >= 0.0
    assert 0.0 <= launch["padding_waste_pct"] <= 100.0
    assert launch["readback_bytes"] > 0
    assert launch["launch_ms"] >= 0.0
    kinds = {k["kernel"]: k["kind"] for k in dev["kernels"]}
    # warmed: the second run reuses the jit cache
    assert kinds.get("plan_topk_batch") in ("cached", "cache_hit",
                                            "compile")
    assert dev["readback_bytes"] > 0
    assert dev["readback_ms"] >= 0


def test_aggregation_child_scope_and_reduce_phase(rest_node):
    """Aggregations profile as structured scopes: the coordinator
    section reports the reduce, and (on the distributed path, pinned in
    the cluster tests below) shards carry `aggs.collect` children."""
    r = _search(rest_node, {"query": {"match": {"title": "fox"}},
                            "profile": True, "size": 0,
                            "aggs": {"m": {"avg": {"field": "rank"}}}})
    coord = r["profile"]["coordinator"]
    assert coord["reduce_batches"] == 1
    assert coord["phases"]["aggs_ns"] >= 0


def test_profile_off_hot_path_allocates_no_profile_objects(
        rest_node, monkeypatch):
    """The guard the acceptance pins: with `profile` absent, NO
    recorder is entered and NO attribution records are allocated on the
    serving path — the stage seam costs one is-None branch."""
    def boom(*a, **k):
        raise AssertionError("profiling() entered on a profile-off path")

    calls = []
    monkeypatch.setattr(profile, "profiling", boom)
    monkeypatch.setattr(profile, "record_device",
                        lambda attrs: calls.append(attrs))
    monkeypatch.setattr(profile, "note_kernel",
                        lambda *a: calls.append(a))
    monkeypatch.setattr(profile, "shard_profile_tree", boom)
    r = _search(rest_node, {"query": {"match": {"title": "fox"}},
                            "size": 5})
    assert "profile" not in r
    assert calls == []
    assert not profile.recording()


def test_kernel_attribution_stage_names_valid():
    """Attribution VALUES name real profile stages. (The key-set drift
    check moved to the static analyzer: ESTPU-JIT03 in
    elasticsearch_tpu/lint — see tests/test_lint.py, which also pins
    the static kernel extraction against runtime discovery.)"""
    for name, stage in profile.KERNEL_ATTRIBUTION.items():
        root = stage.split(".", 1)[0]
        assert root in profile.DEVICE_STAGES + profile.HOST_STAGES \
            + ("aggs",), f"{name} attributes to unknown stage {stage}"


# ------------------------------------------------------------- exemplars

def test_histogram_exemplars_bounded_and_deterministic():
    """One slot per bucket, last-write-wins under an ambient trace —
    deterministic under the seeded clock; untraced observations leave
    no slot."""
    reg = MetricsRegistry()
    reg.observe("lat", 2.0)                   # no ambient trace
    h = reg.histogram("lat")
    assert h.exemplars is None                # lazy: nothing allocated
    with telectx.activate(telectx.TraceContext("n-t1", "n-s1")):
        reg.observe("lat", 3.0)
    with telectx.activate(telectx.TraceContext("n-t2", "n-s2")):
        reg.observe("lat", 4.0)               # same 5ms bucket: wins
        reg.observe("lat", 700.0)             # tail bucket
    d = reg.to_dict()["lat"]
    assert d["exemplars"]["le_5"] == {"value": 4.0, "trace_id": "n-t2"}
    assert d["exemplars"]["le_1000"] == {"value": 700.0,
                                         "trace_id": "n-t2"}
    ex = reg.exemplars_of("lat")
    # tail first: the p99 navigation target leads
    assert ex[0]["bucket"] == "le_1000" and ex[0]["trace_id"] == "n-t2"
    # phase shorthand resolves the .latency suffix
    with telectx.activate(telectx.TraceContext("n-t3", None)):
        reg.observe("search.phase.query.latency", 1.0)
    assert reg.exemplars_of("search.phase.query")[0]["trace_id"] == "n-t3"


def test_traces_exemplar_for_resolves_to_profiled_request(rest_node):
    """`GET /_traces?exemplar_for=search.latency` navigates from a
    histogram bucket to a concrete trace of this node's ring."""
    _search(rest_node, {"query": {"match": {"title": "fox"}},
                        "profile": True, "size": 3})
    status, r = rest_node.rest_controller.dispatch(
        "GET", "/_traces", {"exemplar_for": "search.latency"}, None)
    assert status == 200
    assert r["metric"] == "search.latency"
    assert r["exemplars"], "no exemplar recorded for search.latency"
    ex = r["exemplars"][0]
    assert ex["resolvable"] and ex["root"] == "rest.search"
    status, t = rest_node.rest_controller.dispatch(
        "GET", f"/_traces/{ex['trace_id']}", {}, None)
    assert status == 200 and t["trace_id"] == ex["trace_id"]
    # the exemplars also render in the _nodes/stats histogram block
    status, stats = rest_node.rest_controller.dispatch(
        "GET", "/_nodes/stats", {}, None)
    hist = stats["nodes"][rest_node.node_id]["telemetry"]["metrics"][
        "search.latency"]
    assert hist["exemplars"]


# ------------------------------------------- hot_threads / task stages

def test_hot_threads_reports_task_occupancy(rest_node):
    task = rest_node.task_manager.register(
        "transport", "indices:data/read/search",
        description="indices[idx], source[...]", cancellable=True)
    try:
        with profile.stage_hook(
                lambda st: setattr(task, "profile_stage", st)):
            with profile.span("launch"):
                pass
        status, r = rest_node.rest_controller.dispatch(
            "GET", "/_nodes/hot_threads", {}, None)
        assert status == 200
        text = r["_cat"]
        assert "indices:data/read/search" in text
        assert "stage launch" in text
        assert "indices[idx]" in text
    finally:
        rest_node.task_manager.unregister(task)
    status, r = rest_node.rest_controller.dispatch(
        "GET", "/_nodes/hot_threads", {}, None)
    assert "no running tasks" in r["_cat"]


def test_task_dict_carries_profile_stage_gated_by_detailed():
    from elasticsearch_tpu.transport.tasks import (
        TaskManager,
        filter_task_dicts,
    )
    mgr = TaskManager("n1")
    task = mgr.register("transport", "indices:data/read/search",
                        description="d", cancellable=True)
    try:
        with profile.stage_hook(
                lambda st: setattr(task, "profile_stage", st)):
            with profile.span("bind"):
                pass
            with profile.span("launch"):
                pass
        d = task.to_dict("n1")
        assert d["profile_stage"] == "launch"
        assert filter_task_dicts([dict(d)], detailed=True)[0][
            "profile_stage"] == "launch"
        assert "profile_stage" not in filter_task_dicts(
            [dict(d)], detailed=False)[0]
    finally:
        mgr.unregister(task)


# ------------------------------------------------------------- slowlog

def test_slowlog_carries_trace_id_and_slowest_stage(rest_node):
    r = _search(rest_node, {"query": {"match": {"title": "fox"}},
                            "profile": True, "size": 3})
    entry = rest_node.search_service.slowlog_recent[-1]
    assert entry["index"] == "idx"
    assert entry["trace.id"] == r["_headers"]["trace.id"]
    # the one-line summary names a real stage and a location
    stage = entry["slowest_stage"].split()[0]
    assert stage in profile.DEVICE_STAGES + profile.HOST_STAGES \
        + ("fetch", "query", "reduce", "aggs")
    assert "ms" in entry["slowest_stage"]


def test_slowest_stage_summary_pure():
    from elasticsearch_tpu.search.slowlog import slowest_stage_summary
    assert slowest_stage_summary(None) is None
    assert slowest_stage_summary({}) is None
    resp = {"profile": {"shards": [{
        "id": "[i][0]",
        "searches": [{"query": [{"breakdown": {
            "launch": 5_000_000, "bind": 1_000_000,
            "device_time_in_nanos": 5_000_000,
            "host_time_in_nanos": 1_000_000}}]}],
        "fetch": {"time_in_nanos": 2_000_000}}]}}
    assert slowest_stage_summary(resp) == "launch 5.00ms [i][0]"


# ------------------------------------------------------- 3-node cluster

@pytest.mark.chaos(seed=82)
def test_cluster_profile_tree_replay_identical(tmp_path, chaos_seed):
    """ACCEPTANCE: `profile: true` on a 3-node search returns a
    coordinator-merged ES-shaped per-shard tree with device-kernel
    attribution, byte-identical across two fresh runs of the same
    chaos seed (profile timing reads the deterministic scheduler
    clock)."""
    def one_run(tag):
        cluster = ChaosCluster(3, tmp_path / tag, seed=chaos_seed)
        _setup(cluster)
        coord = cluster.coordinator_excluding("dn-0")
        resp = copy.deepcopy(
            cluster.call(coord.search, "logs", PROFILE_BODY))
        tracer = coord.telemetry.tracer
        return resp, tracer

    one_run("warm")        # warm the process-global jit caches
    resp_a, tracer_a = one_run("a")
    resp_b, _ = one_run("b")
    prof = resp_a["profile"]
    assert json.dumps(prof, sort_keys=True) == \
        json.dumps(resp_b["profile"], sort_keys=True), \
        f"seed={chaos_seed}: profile trees diverged across replays"

    # coordinator-merged shape: one entry per shard, sorted, node-tagged
    assert [s["id"] for s in prof["shards"]] == ["[logs][0]", "[logs][1]"]
    assert all(s["node"] for s in prof["shards"])
    coord_sec = prof["coordinator"]
    assert coord_sec["shard_attempts"] >= 2
    assert set(coord_sec["phases"]) >= {"query_ns", "reduce_ns",
                                        "fetch_ns"}
    # device-kernel attribution on every shard: kernel name, batch
    # wait, padding waste, readback, cache-hit classification
    for shard in prof["shards"]:
        dev = shard["device"]
        launch = dev["launches"][0]
        assert launch["kernel"] == "plan_topk_packed"
        assert launch["batch_wait_ms"] >= 0.0
        assert 0.0 <= launch["padding_waste_pct"] <= 100.0
        assert launch["launch_ms"] >= 0.0
        assert {k["kind"] for k in dev["kernels"]} <= {
            "cached", "cache_hit", "compile"}
        assert dev["readback_bytes"] > 0
        bd = shard["searches"][0]["query"][0]["breakdown"]
        assert bd["device_time_in_nanos"] + bd["host_time_in_nanos"] \
            == shard["searches"][0]["query"][0]["time_in_nanos"]

    # profile ↔ trace cross-link: the stamped trace resolves on the
    # coordinator's ring and roots at the search span
    trace = tracer_a.trace(prof["trace.id"])
    assert trace is not None
    assert any(s["name"] == "search" for s in trace["spans"])


@pytest.mark.chaos(seed=83)
def test_cluster_profile_agg_collect_scope_and_reduce_batches(
        tmp_path, chaos_seed):
    """The PR-7 partial-collect/merge/finalize path profiles as
    structured scopes: shards carry an `aggs.collect` child entry, the
    coordinator section reports reduce batches and the aggs finalize
    phase."""
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster)
    coord = cluster.coordinator_excluding("dn-0")
    body = dict(AGGS_BODY, batched_reduce_size=2)
    resp = cluster.call(coord.search, "logs", body)
    prof = resp["profile"]
    for shard in prof["shards"]:
        aggs = shard["aggregations"]
        assert aggs and aggs[0]["type"] == "aggregations"
        assert aggs[0]["description"] == "m"
        assert "collect" in aggs[0]["breakdown"]
    coord_sec = prof["coordinator"]
    assert coord_sec["reduce_batches"] == resp["num_reduce_phases"]
    assert "aggs_ns" in coord_sec["phases"]


@pytest.mark.chaos(seed=84)
def test_cluster_profile_composes_with_failover(tmp_path, chaos_seed):
    """A shard-copy failure folds into the profile: the coordinator
    section counts the failover attempt while the surviving shard
    entries still profile — observability composes with the PR-1
    partial-results protocol."""
    from elasticsearch_tpu.cluster.search_action import (
        QUERY_PHASE_ACTION)
    from elasticsearch_tpu.testing.faults import ERROR, FaultRule
    cluster = ChaosCluster(3, tmp_path, seed=chaos_seed)
    _setup(cluster)
    coord = cluster.coordinator_excluding("dn-1")
    cluster.injector.add_rule(FaultRule(
        action=QUERY_PHASE_ACTION, node="dn-1", mode=ERROR,
        times=1))
    resp = cluster.call(coord.search, "logs", PROFILE_BODY)
    prof = resp["profile"]
    assert prof["shards"], f"seed={chaos_seed}: no shard profiles"
    assert prof["coordinator"]["shard_attempts"] > 2 or \
        prof["coordinator"]["failover_attempts"] >= 0
    # every shipped shard entry still satisfies the sum invariant
    for shard in prof["shards"]:
        q = shard["searches"][0]["query"][0]
        bd = q["breakdown"]
        assert bd["device_time_in_nanos"] + bd["host_time_in_nanos"] \
            == q["time_in_nanos"]
