"""OIDC realm (ref: x-pack/plugin/security/.../authc/oidc/
OpenIdConnectRealm.java): RS256 ID tokens validate against the OP's
JWKS (issuer/audience/expiry), the principal and groups claims feed
role mappings, and every tamper path is refused."""

import base64
import json
import time

import pytest
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import padding, rsa

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

ISSUER = "https://op.example.com"
CLIENT = "estpu-kibana"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


@pytest.fixture(scope="module")
def op_keys():
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()
    jwks = {"keys": [{
        "kty": "RSA", "kid": "op-key-1", "alg": "RS256", "use": "sig",
        "n": _b64url(pub.n.to_bytes((pub.n.bit_length() + 7) // 8,
                                    "big")),
        "e": _b64url(pub.e.to_bytes(3, "big")),
    }]}
    return key, jwks


def mint(key, claims, kid="op-key-1", alg="RS256"):
    header = _b64url(json.dumps({"alg": alg, "kid": kid}).encode())
    payload = _b64url(json.dumps(claims).encode())
    sig = key.sign(f"{header}.{payload}".encode(), padding.PKCS1v15(),
                   hashes.SHA256())
    return f"{header}.{payload}.{_b64url(sig)}"


@pytest.fixture()
def node(tmp_path, op_keys):
    _key, jwks = op_keys
    jwks_path = tmp_path / "jwks.json"
    jwks_path.write_text(json.dumps(jwks))
    n = Node(settings=Settings.from_dict({
        "xpack": {"security": {
            "enabled": True,
            "authc": {"oidc": {
                "op": {"issuer": ISSUER,
                       "jwks_path": str(jwks_path)},
                "rp": {"client_id": CLIENT}}}}},
        "bootstrap": {"password": "s3cret"},
    }), data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, headers=None, expect=200):
    status, r = node.rest_controller.dispatch(method, path, {}, body,
                                              headers=headers)
    assert status == expect, (status, r)
    return r


def basic(user, pw):
    return {"Authorization": "Basic "
            + base64.b64encode(f"{user}:{pw}".encode()).decode()}


def claims(**over):
    c = {"iss": ISSUER, "aud": CLIENT, "sub": "alice",
         "exp": time.time() + 600, "groups": ["observers"]}
    c.update(over)
    return c


def test_oidc_token_authenticates_with_group_roles(node, op_keys):
    key, _ = op_keys
    call(node, "PUT", "/_security/role_mapping/oidc-map",
         {"roles": ["monitoring_user"],
          "rules": {"field": {"groups": "observers"}}},
         headers=basic("elastic", "s3cret"))
    tok = mint(key, claims())
    me = call(node, "GET", "/_security/_authenticate",
              headers={"Authorization": f"Bearer {tok}"})
    assert me["username"] == "alice"
    assert "monitoring_user" in me["roles"]
    # the mapped role authorizes cluster reads
    call(node, "GET", "/_cluster/health",
         headers={"Authorization": f"Bearer {tok}"})


def test_oidc_refusals(node, op_keys):
    key, _ = op_keys

    def refuse(tok):
        call(node, "GET", "/_security/_authenticate",
             headers={"Authorization": f"Bearer {tok}"}, expect=401)

    refuse(mint(key, claims(iss="https://evil.example.com")))
    refuse(mint(key, claims(aud="other-client")))
    refuse(mint(key, claims(exp=time.time() - 10)))
    # signature from a DIFFERENT key (kid spoofed to the OP's)
    rogue = rsa.generate_private_key(public_exponent=65537,
                                     key_size=2048)
    refuse(mint(rogue, claims()))
    # tampered payload keeps the old signature
    good = mint(key, claims())
    h, p, s = good.split(".")
    forged_p = _b64url(json.dumps(claims(sub="admin")).encode())
    refuse(f"{h}.{forged_p}.{s}")
