"""Operational-layer tests: index open/close, frozen indices, searchable
snapshots, geoip/user-agent processors, hot threads, deprecation,
autoscaling, slow logs, extended _cat family."""

import pytest

from elasticsearch_tpu.node import Node


@pytest.fixture()
def node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, expect=200, **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, r
    return r


def _seed(node, name="idx", n=3):
    node.indices_service.create_index(name, {}, {
        "properties": {"v": {"type": "long"}}})
    idx = node.indices_service.get(name)
    for i in range(n):
        idx.index_doc(str(i), {"v": i})
    idx.refresh()
    return idx


def test_close_open_index(node):
    _seed(node)
    call(node, "POST", "/idx/_close")
    # explicit search on closed index → 400
    status, r = node.rest_controller.dispatch(
        "POST", "/idx/_search", {}, {"size": 1})
    assert status == 400 and "closed" in str(r)
    # writes blocked with 403
    status, r = node.rest_controller.dispatch(
        "PUT", "/idx/_doc/9", {}, {"v": 9})
    assert status == 403
    # wildcard search skips it
    r = call(node, "POST", "/_search", {"size": 10})
    assert r["hits"]["total"]["value"] == 0
    call(node, "POST", "/idx/_open")
    r = call(node, "POST", "/idx/_search", {"size": 10})
    assert r["hits"]["total"]["value"] == 3


def test_freeze_unfreeze(node):
    idx = _seed(node)
    call(node, "POST", "/idx/_freeze")
    # frozen is searchable but write-blocked
    r = call(node, "POST", "/idx/_search", {"size": 10})
    assert r["hits"]["total"]["value"] == 3
    status, _ = node.rest_controller.dispatch(
        "PUT", "/idx/_doc/9", {}, {"v": 9})
    assert status == 403
    # no device-resident segments linger after a frozen search
    assert not idx.device_cache._cache
    r = call(node, "GET", "/_migration/deprecations")
    assert "idx" in r["index_settings"]
    call(node, "POST", "/idx/_unfreeze")
    idx.index_doc("9", {"v": 9})


def test_mount_searchable_snapshot(node):
    _seed(node, "src", n=4)
    call(node, "PUT", "/_snapshot/repo1",
         {"type": "fs", "settings": {"location": "repo1"}})
    call(node, "PUT", "/_snapshot/repo1/snap1", {"indices": "src"})
    r = call(node, "POST", "/_snapshot/repo1/snap1/_mount",
             {"index": "src", "renamed_index": "mounted"})
    assert r["snapshot"]["indices"] == ["mounted"]
    got = call(node, "POST", "/mounted/_search", {"size": 10})
    assert got["hits"]["total"]["value"] == 4
    # read-only: writes rejected
    status, _ = node.rest_controller.dispatch(
        "PUT", "/mounted/_doc/z", {}, {"v": 99})
    assert status == 403
    stats = call(node, "GET", "/_searchable_snapshots/stats")
    assert stats["indices"]["mounted"]["snapshot"] == "snap1"


def test_geoip_processor(node):
    node.ingest_service.put_pipeline("geo", {"processors": [
        {"geoip": {"field": "ip"}}]})
    node.indices_service.create_index("visits", {}, None)
    call(node, "PUT", "/visits/_doc/1", {"ip": "192.0.2.44"},
         expect=201, pipeline="geo")
    node.indices_service.get("visits").refresh()
    r = call(node, "POST", "/visits/_search", {"size": 1})
    src = r["hits"]["hits"][0]["_source"]
    assert src["geoip"]["country_name"] == "TEST-NET-1"
    assert src["geoip"]["location"] == {"lat": 0.0, "lon": 0.0}


def test_geoip_custom_database(node, tmp_path):
    import json
    db = tmp_path / "geo.json"
    db.write_text(json.dumps([{
        "network": "10.1.0.0/16", "country_iso_code": "DE",
        "country_name": "Germany", "city_name": "Berlin"}]))
    node.ingest_service.put_pipeline("geo", {"processors": [
        {"geoip": {"field": "ip", "database_file": str(db)}}]})
    r = node.ingest_service.simulate("geo", [
        {"_source": {"ip": "10.1.2.3"}}])
    assert r["docs"][0]["doc"]["_source"]["geoip"]["city_name"] == "Berlin"


def test_user_agent_processor(node):
    node.ingest_service.put_pipeline("ua", {"processors": [
        {"user_agent": {"field": "agent"}}]})
    ua = ("Mozilla/5.0 (Windows NT 10.0; Win64; x64) "
          "AppleWebKit/537.36 (KHTML, like Gecko) "
          "Chrome/120.0.0.0 Safari/537.36")
    r = node.ingest_service.simulate("ua", [{"_source": {"agent": ua}}])
    parsed = r["docs"][0]["doc"]["_source"]["user_agent"]
    assert parsed["name"] == "Chrome"
    assert parsed["major"] == "120"
    assert parsed["os"]["name"] == "Windows"
    r = node.ingest_service.simulate("ua", [{"_source": {
        "agent": "curl/8.4.0"}}])
    assert r["docs"][0]["doc"]["_source"]["user_agent"]["name"] == "curl"


def test_hot_threads(node):
    # PR-8: the endpoint reports real occupancy — top running TASKS
    # (scheduler-clock running time + current profile stage) instead of
    # a Python-thread stack dump (tests/test_profile_api.py covers the
    # task-occupancy rendering in depth)
    r = call(node, "GET", "/_nodes/hot_threads")
    assert node.name in r["_cat"]
    assert "no running tasks" in r["_cat"] or "occupancy by task" in r["_cat"]


def test_autoscaling(node):
    _seed(node)
    call(node, "PUT", "/_autoscaling/policy/data", {
        "roles": ["data"], "deciders": {"fixed": {}}})
    r = call(node, "GET", "/_autoscaling/policy/data")
    assert r["data"]["policy"]["roles"] == ["data"]
    r = call(node, "GET", "/_autoscaling/capacity")
    assert "data" in r["policies"]
    assert r["policies"]["data"]["required_capacity"]["total"][
        "storage"] >= 0
    call(node, "DELETE", "/_autoscaling/policy/data")
    call(node, "GET", "/_autoscaling/policy/data", expect=404)


def test_search_slowlog(node):
    idx = _seed(node)
    recent = node.search_service.slowlog_recent
    idx.update_settings(
        {"index.search.slowlog.threshold.query.warn": "0ms"})
    call(node, "POST", "/idx/_search", {"size": 1})
    assert recent
    assert recent[-1]["index"] == "idx"
    assert recent[-1]["level"] == "warn"
    # -1 disables the level
    recent.clear()
    idx.update_settings(
        {"index.search.slowlog.threshold.query.warn": "-1"})
    call(node, "POST", "/idx/_search", {"size": 1})
    assert not recent


def test_cat_family(node):
    _seed(node)
    call(node, "PUT", "/_snapshot/r1",
         {"type": "fs", "settings": {"location": "r1"}})
    call(node, "PUT", "/_snapshot/r1/s1", {"indices": "idx"})
    assert node.name in call(node, "GET", "/_cat/nodes")["_cat"]
    assert node.name in call(node, "GET", "/_cat/master")["_cat"]
    assert "idx" in call(node, "GET", "/_cat/segments")["_cat"]
    assert "r1 fs" in call(node, "GET", "/_cat/repositories")["_cat"]
    assert "s1 SUCCESS" in call(node, "GET", "/_cat/snapshots/r1")["_cat"]
    assert "idx" in call(node, "GET", "/_cat/recovery")["_cat"]
    call(node, "GET", "/_cat/thread_pool")
    call(node, "GET", "/_cat/plugins")
    call(node, "GET", "/_cat/allocation")
    call(node, "GET", "/_cat/nodeattrs")
    call(node, "GET", "/_cat/pending_tasks")


def test_closed_index_admin_operations(node):
    _seed(node)
    call(node, "POST", "/idx/_close")
    # closed indices still serve admin reads and are deletable
    call(node, "GET", "/idx/_mapping")
    call(node, "GET", "/idx/_settings")
    call(node, "POST", "/idx/_close")               # idempotent
    # doc reads are blocked on closed indices
    status, _ = node.rest_controller.dispatch("GET", "/idx/_doc/0", {})
    assert status == 400
    call(node, "DELETE", "/idx")
    assert not node.indices_service.has("idx")


def test_open_all(node):
    _seed(node, "a1")
    _seed(node, "a2")
    call(node, "POST", "/a1/_close")
    call(node, "POST", "/a2/_close")
    call(node, "POST", "/_all/_open")
    assert not node.indices_service.get("a1").is_closed
    assert not node.indices_service.get("a2").is_closed


def test_frozen_eviction_after_scroll(node):
    idx = _seed(node, "fz", n=5)
    call(node, "POST", "/fz/_freeze")
    r = call(node, "POST", "/fz/_search", {"size": 2}, scroll="1m")
    sid = r["_scroll_id"]
    node.search_service.scroll(sid)
    assert not idx.device_cache._cache


def test_mapper_size(node):
    node.indices_service.create_index("sz", {}, {
        "_size": {"enabled": True},
        "properties": {"body": {"type": "text"}}})
    idx = node.indices_service.get("sz")
    idx.index_doc("1", {"body": "tiny"})
    idx.index_doc("2", {"body": "a much longer document body text here"})
    idx.refresh()
    r = node.search_service.search("sz", {
        "size": 2, "sort": [{"_size": {"order": "desc"}}]})
    ids = [h["_id"] for h in r["hits"]["hits"]]
    assert ids == ["2", "1"]
    # aggregatable too
    r = node.search_service.search("sz", {"size": 0, "aggs": {
        "m": {"max": {"field": "_size"}}}})
    assert r["aggregations"]["m"]["value"] > 20
    # round-trips through the mapping API
    status, m = node.rest_controller.dispatch("GET", "/sz/_mapping", {})
    assert m["sz"]["mappings"]["_size"] == {"enabled": True}


def test_indexing_slowlog(node):
    idx = _seed(node, "slow")
    idx.update_settings(
        {"index.indexing.slowlog.threshold.index.warn": "0ms"})
    idx.index_doc("x", {"v": 1})
    assert idx.indexing_slowlog_recent
    assert idx.indexing_slowlog_recent[-1]["id"] == "x"


def test_voting_exclusions_and_allocation_explain_rest(tmp_path):
    from elasticsearch_tpu.node import Node

    node = Node(data_path=str(tmp_path / "vx"))
    try:
        st, r = node.rest_controller.dispatch(
            "POST", "/_cluster/voting_config_exclusions",
            {"node_names": "other-node"}, None)
        assert (st, r["acknowledged"]) == (200, True)
        # excluding the only master-eligible node is refused
        st, r = node.rest_controller.dispatch(
            "POST", "/_cluster/voting_config_exclusions",
            {"node_names": node.name}, None)
        assert st == 400
        st, r = node.rest_controller.dispatch(
            "DELETE", "/_cluster/voting_config_exclusions", None, None)
        assert st == 200

        node.rest_controller.dispatch("PUT", "/ae", None, None)
        st, r = node.rest_controller.dispatch(
            "POST", "/_cluster/allocation/explain", None,
            {"index": "ae", "shard": 0, "primary": True})
        assert st == 200
        assert r["current_state"] == "started"
        assert r["current_node"]["name"] == node.name
        st, r = node.rest_controller.dispatch(
            "POST", "/_cluster/allocation/explain", None,
            {"index": "ae", "shard": 9})
        assert st == 400
    finally:
        node.close()


def test_task_results_survive_restart(tmp_path):
    """Completed background-task results persist in the .tasks system
    index (ref: the tasks module / TaskResultsService) and resolve
    through GET /_tasks/{id} after a restart."""
    import time as _time
    from elasticsearch_tpu.node import Node

    node = Node(data_path=str(tmp_path / "tk"))
    try:
        node.rest_controller.dispatch("PUT", "/src", None, {
            "mappings": {"properties": {"x": {"type": "long"}}}})
        for i in range(5):
            node.rest_controller.dispatch("PUT", f"/src/_doc/{i}", None,
                                          {"x": i})
        node.rest_controller.dispatch("POST", "/src/_refresh", None, None)
        st, r = node.rest_controller.dispatch(
            "POST", "/_reindex", {"wait_for_completion": "false"},
            {"source": {"index": "src"}, "dest": {"index": "dst"}})
        assert st == 200
        task_id = r["task"]
        deadline = _time.time() + 20
        while _time.time() < deadline:
            st, r = node.rest_controller.dispatch(
                "GET", f"/_tasks/{task_id}", None, None)
            if r.get("completed"):
                break
            _time.sleep(0.05)
        assert r["completed"] and r["response"]["total"] == 5
        data_path = node.data_path
    finally:
        node.close()

    node2 = Node(data_path=data_path)
    try:
        st, r = node2.rest_controller.dispatch(
            "GET", f"/_tasks/{task_id}", None, None)
        # node ids differ across restarts; the .tasks doc still resolves
        # for bare numeric ids (parsed with empty node scope)
        bare = task_id.split(":", 1)[1]
        st, r = node2.rest_controller.dispatch(
            "GET", f"/_tasks/{bare}", None, None)
        assert st == 200 and r["completed"], r
        assert r["response"]["total"] == 5
    finally:
        node2.close()


def test_sd_notify_protocol(tmp_path, monkeypatch):
    """sd_notify datagrams reach the NOTIFY_SOCKET (ref:
    modules/systemd SystemdPlugin)."""
    import socket as _socket
    from elasticsearch_tpu.common import systemd

    sock_path = str(tmp_path / "notify.sock")
    srv = _socket.socket(_socket.AF_UNIX, _socket.SOCK_DGRAM)
    srv.bind(sock_path)
    srv.settimeout(5)
    try:
        monkeypatch.setenv("NOTIFY_SOCKET", sock_path)
        assert systemd.notify_ready()
        assert srv.recv(64) == b"READY=1"
        assert systemd.notify_extend_timeout(30_000_000)
        assert srv.recv(64) == b"EXTEND_TIMEOUT_USEC=30000000"
        assert systemd.notify_stopping()
        assert srv.recv(64) == b"STOPPING=1"
        monkeypatch.delenv("NOTIFY_SOCKET")
        assert systemd.notify_ready() is False   # not under systemd
    finally:
        srv.close()


def test_frozen_search_uses_throttled_pool(tmp_path):
    """Searches targeting only frozen indices run on the single-threaded
    search_throttled pool (ref: ThreadPool.Names.SEARCH_THROTTLED)."""
    from elasticsearch_tpu.node import Node

    node = Node(data_path=str(tmp_path / "fz"))

    def call(method, path, body=None, expect=200, **params):
        st, r = node.rest_controller.dispatch(method, path, params, body)
        assert st == expect, r
        return r

    try:
        call("PUT", "/coldidx", {"mappings": {"properties": {
            "t": {"type": "text"}}}})
        call("PUT", "/coldidx/_doc/1", {"t": "ice"}, expect=201)
        call("POST", "/coldidx/_refresh")
        call("POST", "/coldidx/_freeze")
        before = node.threadpool.executor("search_throttled") \
            .stats()["completed"]
        r = call("POST", "/coldidx/_search",
                 {"query": {"match": {"t": "ice"}}})
        assert r["hits"]["total"]["value"] == 1
        after = node.threadpool.executor("search_throttled") \
            .stats()["completed"]
        assert after == before + 1
        # hot indices stay off the throttled pool
        call("PUT", "/hotidx", None)
        call("PUT", "/hotidx/_doc/1", {"x": 1}, expect=201)
        call("POST", "/hotidx/_refresh")
        call("POST", "/hotidx/_search", {"query": {"match_all": {}}})
        assert node.threadpool.executor("search_throttled") \
            .stats()["completed"] == after
    finally:
        node.close()


def test_recovery_api_reports_local_store_shards(node):
    """GET /_recovery + /{index}/_recovery: every local shard shows a
    completed local_store recovery with honest on-disk bytes."""
    _seed(node, n=5)
    r = call(node, "GET", "/_recovery")
    assert "idx" in r
    shards = r["idx"]["shards"]
    assert len(shards) == 1
    rec = shards[0]
    assert rec["type"] == "local_store" and rec["stage"] == "DONE"
    assert rec["index_files"]["recovered_bytes"] > 0
    assert rec["index_files"]["recovered_bytes"] == \
        rec["index_files"]["total_bytes"]
    assert rec["translog"]["ops_replayed"] >= 0
    assert rec["source_node"] == rec["target_node"] == node.name
    # the index-scoped form matches, and unknown indices 404
    assert call(node, "GET", "/idx/_recovery") == {"idx": r["idx"]}
    call(node, "GET", "/nope/_recovery", expect=404)
    # _cat renders one row per shard from the same entries
    cat = call(node, "GET", "/_cat/recovery")["_cat"]
    assert "idx 0" in cat and "local_store" in cat and "done" in cat
    # and the node-stats surface carries the same section
    stats = call(node, "GET", "/_nodes/stats")
    (node_stats,) = stats["nodes"].values()
    assert node_stats["recoveries"] == shards


def test_cluster_reroute_single_node_explains_no(node):
    """POST /_cluster/reroute on the single-node surface: commands
    validate and explain a NO — there is no second node to move to."""
    _seed(node)
    r = call(node, "POST", "/_cluster/reroute", {
        "commands": [{"move": {"index": "idx", "shard": 0,
                               "from_node": node.node_id,
                               "to_node": "other"}}]}, explain="true")
    assert r["acknowledged"] is True
    (entry,) = r["explanations"]
    assert entry["command"] == "move" and entry["accepted"] is False
    assert entry["decisions"][0]["decision"] == "NO"
    # no explain flag → no explanations section, still acknowledged
    r = call(node, "POST", "/_cluster/reroute", {
        "commands": [{"cancel": {"index": "idx", "shard": 0,
                                 "node": node.node_id}}]})
    assert r == {"acknowledged": True}
    # malformed / unknown commands are 400s
    call(node, "POST", "/_cluster/reroute",
         {"commands": [{"bogus": {}}]}, expect=400)
    call(node, "POST", "/_cluster/reroute",
         {"commands": [{"move": {"index": "ghost", "shard": 0,
                                 "from_node": "a", "to_node": "b"}}]},
         expect=404)
