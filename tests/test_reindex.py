"""Reindex family tests (ref: modules/reindex — scroll+bulk worker with
scripts, conflicts=proceed, max_docs, background tasks)."""

import time

import pytest

from elasticsearch_tpu.common.errors import ScriptException
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.reindex.worker import UpdateScript, _Ctx


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def seed(node, index="src", n=25):
    for i in range(n):
        status, _ = node.rest_controller.dispatch(
            "PUT", f"/{index}/_doc/{i}", None,
            {"title": f"doc {i}", "n": i,
             "tag": "even" if i % 2 == 0 else "odd"})
        assert status in (200, 201)
    do(node, "POST", f"/{index}/_refresh")


def test_reindex_basic(node):
    seed(node)
    r = do(node, "POST", "/_reindex", body={
        "source": {"index": "src"}, "dest": {"index": "dst"}})
    assert r["total"] == 25 and r["created"] == 25
    assert r["failures"] == []
    do(node, "POST", "/dst/_refresh")
    c = do(node, "GET", "/dst/_count")
    assert c["count"] == 25


def test_reindex_with_query_and_max_docs(node):
    seed(node)
    r = do(node, "POST", "/_reindex", body={
        "source": {"index": "src", "query": {"term": {"tag": "even"}}},
        "dest": {"index": "dst2"}, "max_docs": 5})
    assert r["total"] == 5


def test_reindex_script_and_noop_delete(node):
    seed(node, n=10)
    r = do(node, "POST", "/_reindex", body={
        "source": {"index": "src"},
        "dest": {"index": "dst3"},
        "script": {"source":
                   "if ctx._source.n > 7:\n    ctx.op = 'noop'\n"
                   "ctx._source.boosted = ctx._source.n * 2"},
    })
    # n in {8,9} -> noop
    assert r["noops"] == 2 and r["created"] == 8
    do(node, "POST", "/dst3/_refresh")
    got = do(node, "GET", "/dst3/_doc/3")
    assert got["_source"]["boosted"] == 6


def test_reindex_op_type_create_conflicts(node):
    seed(node, n=6)
    do(node, "POST", "/_reindex", body={
        "source": {"index": "src"}, "dest": {"index": "dst4"}})
    # second run with op_type create → all version conflicts, proceed
    r = do(node, "POST", "/_reindex", body={
        "conflicts": "proceed",
        "source": {"index": "src"},
        "dest": {"index": "dst4", "op_type": "create"}})
    assert r["version_conflicts"] == 6 and r["created"] == 0
    # abort mode records a failure
    r2 = do(node, "POST", "/_reindex", body={
        "source": {"index": "src"},
        "dest": {"index": "dst4", "op_type": "create"}})
    assert r2["version_conflicts"] >= 1 and r2["failures"]


def test_update_by_query_script(node):
    seed(node, n=8)
    r = do(node, "POST", "/src/_update_by_query",
           params={"refresh": "true"},
           body={"query": {"term": {"tag": "odd"}},
                 "script": {"source": "ctx._source.flagged = True"}})
    assert r["updated"] == 4
    got = do(node, "GET", "/src/_doc/1")
    assert got["_source"]["flagged"] is True
    got2 = do(node, "GET", "/src/_doc/2")
    assert "flagged" not in got2["_source"]


def test_update_by_query_params_and_increment(node):
    seed(node, n=4)
    do(node, "POST", "/src/_update_by_query",
       params={"refresh": "true"},
       body={"script": {"source": "ctx._source.n += params.step",
                        "params": {"step": 100}}})
    got = do(node, "GET", "/src/_doc/2")
    assert got["_source"]["n"] == 102


def test_delete_by_query(node):
    seed(node, n=20)
    r = do(node, "POST", "/src/_delete_by_query",
           params={"refresh": "true"},
           body={"query": {"range": {"n": {"gte": 10}}}})
    assert r["deleted"] == 10
    c = do(node, "GET", "/src/_count")
    assert c["count"] == 10


def test_script_string_literals_preserved():
    s = UpdateScript("ctx._source.tag = 'a && b; !c'")
    ctx = _Ctx({}, "i", "1", 1)
    s.run(ctx)
    assert ctx._source._data["tag"] == "a && b; !c"


def test_reindex_external_versioning(node):
    seed(node, n=3)
    do(node, "POST", "/_reindex", body={
        "source": {"index": "src"},
        "dest": {"index": "dstv", "version_type": "external"}})
    # bump a dest doc so its version outruns the source's
    do(node, "GET", "/dstv/_doc/1")
    node.indices_service.get("dstv").index_doc("1", {"n": 999})
    r = do(node, "POST", "/_reindex", body={
        "conflicts": "proceed",
        "source": {"index": "src"},
        "dest": {"index": "dstv", "version_type": "external"}})
    assert r["version_conflicts"] >= 1


def test_search_version_flag(node):
    seed(node, n=2)
    r = do(node, "POST", "/src/_search",
           body={"version": True, "seq_no_primary_term": True})
    hit = r["hits"]["hits"][0]
    assert hit["_version"] == 1
    assert "_seq_no" in hit and "_primary_term" in hit


def test_reindex_background_task(node):
    seed(node, n=12)
    r = do(node, "POST", "/_reindex", params={"wait_for_completion": "false"},
           body={"source": {"index": "src"}, "dest": {"index": "dstbg"}})
    task_id = r["task"]
    deadline = time.time() + 10
    while time.time() < deadline:
        tr = do(node, "GET", f"/_tasks/{task_id}")
        if tr.get("completed"):
            assert tr["response"]["created"] == 12
            break
        time.sleep(0.05)
    else:
        raise AssertionError("background reindex did not finish")


def test_update_script_sandbox():
    s = UpdateScript("ctx._source.x = 1")
    ctx = _Ctx({"x": 0}, "i", "1", 1)
    s.run(ctx)
    assert ctx._source._data["x"] == 1
    with pytest.raises(ScriptException):
        UpdateScript("__import__('os')")
    with pytest.raises(ScriptException):
        UpdateScript("open('/etc/passwd')")
    with pytest.raises(ScriptException):
        UpdateScript("ctx.__class__")


def test_reindex_remove_field_script(node):
    seed(node, n=3)
    do(node, "POST", "/_reindex", body={
        "source": {"index": "src"}, "dest": {"index": "dst5"},
        "script": {"source": "ctx._source.remove('tag')"}})
    do(node, "POST", "/dst5/_refresh")
    got = do(node, "GET", "/dst5/_doc/0")
    assert "tag" not in got["_source"]


def test_reindex_from_remote(tmp_path):
    """Reindex from a REMOTE cluster over HTTP (ref: modules/reindex
    remote mode / RemoteScrollableHitSource), including basic auth
    against a secured source."""
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.node import Node

    src_node = Node(settings=Settings.from_dict({
        "xpack": {"security": {"enabled": True}},
        "bootstrap": {"password": "remotepw"},
    }), data_path=str(tmp_path / "srcnode"))
    dst_node = Node(data_path=str(tmp_path / "dstnode"))
    try:
        src_port = src_node.start(0)
        import base64
        auth = {"Authorization": "Basic " + base64.b64encode(
            b"elastic:remotepw").decode()}

        def src_call(method, path, body=None, **params):
            st, r = src_node.rest_controller.dispatch(
                method, path, params, body, headers=auth)
            assert st in (200, 201), r
            return r

        src_call("PUT", "/logs", {"mappings": {"properties": {
            "msg": {"type": "text"}, "n": {"type": "long"}}}})
        for i in range(25):
            src_call("PUT", f"/logs/_doc/{i}",
                     {"msg": f"event {i}", "n": i})
        src_call("POST", "/logs/_refresh")

        st, r = dst_node.rest_controller.dispatch(
            "POST", "/_reindex", None, {
                "source": {
                    "remote": {"host": f"http://127.0.0.1:{src_port}",
                               "username": "elastic",
                               "password": "remotepw"},
                    "index": "logs",
                    "size": 10,
                    "query": {"range": {"n": {"gte": 5}}},
                },
                "dest": {"index": "copied"},
            })
        assert st == 200, r
        assert r["created"] == 20
        dst_node.rest_controller.dispatch("POST", "/copied/_refresh",
                                          None, None)
        st, r = dst_node.rest_controller.dispatch(
            "POST", "/copied/_search", None,
            {"query": {"match_all": {}}, "size": 0,
             "track_total_hits": True})
        assert r["hits"]["total"]["value"] == 20

        # bad credentials surface as an error, not silence
        st, r = dst_node.rest_controller.dispatch(
            "POST", "/_reindex", None, {
                "source": {"remote": {
                    "host": f"http://127.0.0.1:{src_port}",
                    "username": "elastic", "password": "wrong"},
                    "index": "logs"},
                "dest": {"index": "nope"}})
        assert st >= 400
    finally:
        src_node.close()
        dst_node.close()
