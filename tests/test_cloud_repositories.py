"""Cloud repository backends against in-process fixtures (the
reference's s3-fixture strategy: a minimal service emulation verifies
the CLIENT — auth headers included — without network egress)."""

import base64
import hashlib
import hmac
import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from elasticsearch_tpu.common.keystore import KEYSTORE_FILENAME, KeyStore
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

ACCESS, SECRET = "AKIDEXAMPLE", "wJalrXUtnFEMI"


class _FakeCloudHandler(BaseHTTPRequestHandler):
    """One fixture speaking enough S3 (XML), GCS (JSON) and Azure to
    satisfy the clients. Objects live in a dict on the server."""

    def log_message(self, *a):
        pass

    # --------------------------------------------------------------- util
    def _blobs(self):
        return self.server.blobs

    def _send(self, status, body=b"", ctype="application/octet-stream"):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def _read_body(self):
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _verify_s3(self):
        auth = self.headers.get("Authorization", "")
        m = re.match(
            r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d+)/([^/]+)/s3/"
            r"aws4_request, SignedHeaders=([^,]+), Signature=([0-9a-f]+)",
            auth)
        if not m or m.group(1) != ACCESS:
            return False
        # recompute the signature exactly as AWS does
        datestamp, region, signed_headers, got = (
            m.group(2), m.group(3), m.group(4), m.group(5))
        u = urllib.parse.urlsplit(self.path)
        q = urllib.parse.parse_qsl(u.query, keep_blank_values=True)
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(q))
        payload_hash = self.headers["x-amz-content-sha256"]
        canonical_headers = (
            f"host:{self.headers['Host']}\n"
            f"x-amz-content-sha256:{payload_hash}\n"
            f"x-amz-date:{self.headers['x-amz-date']}\n")
        canonical = "\n".join([
            self.command, u.path or "/",
            canonical_query, canonical_headers, signed_headers,
            payload_hash])
        scope = f"{datestamp}/{region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", self.headers["x-amz-date"], scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def h(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(("AWS4" + SECRET).encode(), datestamp)
        k = h(k, region)
        k = h(k, "s3")
        k = h(k, "aws4_request")
        want = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return hmac.compare_digest(want, got)

    # ------------------------------------------------------------ routing
    def _dispatch(self):
        u = urllib.parse.urlsplit(self.path)
        path = urllib.parse.unquote(u.path)
        q = dict(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
        mode = self.server.mode
        blobs = self._blobs()

        if mode == "s3" and not self._verify_s3():
            self._send(403, b"<Error>SignatureDoesNotMatch</Error>")
            return

        if mode == "gcs":
            if self.headers.get("Authorization") != "Bearer tok123":
                self._send(401, b"{}")
                return
            if path.startswith("/upload/storage/v1/b/"):
                blobs[q["name"]] = self._read_body()
                self._send(200, b"{}", "application/json")
                return
            m = re.match(r"/storage/v1/b/[^/]+/o/(.+)$", path)
            if m:
                name = m.group(1)
                if self.command == "DELETE":
                    blobs.pop(name, None)
                    self._send(204)
                elif name not in blobs:
                    self._send(404, b"{}")
                elif q.get("alt") == "media":
                    self._send(200, blobs[name])
                else:   # metadata GET (existence check)
                    self._send(200, json.dumps(
                        {"name": name,
                         "size": str(len(blobs[name]))}).encode(),
                        "application/json")
                return
            if re.match(r"/storage/v1/b/[^/]+/o$", path):
                prefix = q.get("prefix", "")
                keys = [k for k in sorted(blobs) if k.startswith(prefix)]
                start = 0
                if q.get("pageToken"):
                    start = keys.index(q["pageToken"]) + 1
                page = keys[start:start + 3]     # force pagination
                doc = {"items": [{"name": k} for k in page]}
                if start + 3 < len(keys):
                    doc["nextPageToken"] = page[-1]
                self._send(200, json.dumps(doc).encode(),
                           "application/json")
                return
            self._send(404, b"{}")
            return

        # s3 + azure share path-style object storage
        if mode == "azure":
            auth = self.headers.get("Authorization", "")
            want = hmac.new(b"azkey123",
                            f"{self.command}\n{self.path}".encode(),
                            hashlib.sha256).hexdigest()
            if auth != f"SharedKey devaccount:{want}":
                self._send(403)
                return

        parts = path.lstrip("/").split("/", 1)
        key = parts[1] if len(parts) > 1 else ""
        if "list-type" in q or q.get("comp") == "list":
            prefix = q.get("prefix", "")
            tag = "Key" if mode == "s3" else "Name"
            keys = [k for k in sorted(blobs) if k.startswith(prefix)]
            marker = q.get("continuation-token") or q.get("marker")
            start = keys.index(marker) + 1 if marker in keys else 0
            page = keys[start:start + 3]         # force pagination
            xml = "".join(f"<{tag}>{k}</{tag}>" for k in page)
            if start + 3 < len(keys):
                nxt = ("NextContinuationToken" if mode == "s3"
                       else "NextMarker")
                xml += f"<{nxt}>{page[-1]}</{nxt}>"
            self._send(200, f"<List>{xml}</List>".encode(),
                       "application/xml")
            return
        if self.command == "PUT":
            blobs[key] = self._read_body()
            self._send(200)
        elif self.command in ("GET", "HEAD"):
            if key in blobs:
                self._send(200, blobs[key])
            else:
                self._send(404, b"<Error>NoSuchKey</Error>")
        elif self.command == "DELETE":
            blobs.pop(key, None)
            self._send(204)
        else:
            self._send(405)

    do_GET = do_PUT = do_DELETE = do_HEAD = do_POST = _dispatch


@pytest.fixture()
def fixture_server():
    servers = []

    def start(mode):
        srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeCloudHandler)
        srv.mode = mode
        srv.blobs = {}
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return f"http://127.0.0.1:{srv.server_address[1]}"

    yield start
    for srv in servers:
        srv.shutdown()


def _node_with_keystore(tmp_path):
    data = tmp_path / "data"
    data.mkdir()
    ks = KeyStore.create(str(data / KEYSTORE_FILENAME), "")
    ks.set_string("s3.client.default.access_key", ACCESS)
    ks.set_string("s3.client.default.secret_key", SECRET)
    ks.set_string("gcs.client.default.credentials_file", "tok123")
    ks.set_string("azure.client.default.account", "devaccount")
    ks.set_string("azure.client.default.key", "azkey123")
    ks.save("")
    return Node(data_path=str(data))


def _snapshot_roundtrip(node, repo_settings, repo_type):
    st, r = node.rest_controller.dispatch(
        "PUT", "/_snapshot/cloud", None,
        {"type": repo_type, "settings": repo_settings})
    assert st == 200, r
    node.rest_controller.dispatch("PUT", "/docs", None, {
        "mappings": {"properties": {"t": {"type": "text"}}}})
    for i in range(20):
        node.rest_controller.dispatch("PUT", f"/docs/_doc/{i}", None,
                                      {"t": f"hello world {i}"})
    node.rest_controller.dispatch("POST", "/docs/_refresh", None, None)
    st, r = node.rest_controller.dispatch(
        "PUT", "/_snapshot/cloud/snap1",
        {"wait_for_completion": "true"}, {"indices": "docs"})
    assert st == 200, r
    st, r = node.rest_controller.dispatch(
        "POST", "/_snapshot/cloud/snap1/_restore", None,
        {"indices": "docs", "rename_pattern": "^docs$",
         "rename_replacement": "docs2"})
    assert st == 200, r
    st, r = node.rest_controller.dispatch(
        "POST", "/docs2/_search", None,
        {"query": {"match": {"t": "hello"}}, "size": 30})
    assert st == 200 and r["hits"]["total"]["value"] == 20


def test_s3_repository_roundtrip(tmp_path, fixture_server):
    endpoint = fixture_server("s3")
    node = _node_with_keystore(tmp_path)
    try:
        _snapshot_roundtrip(node, {"bucket": "b1", "endpoint": endpoint,
                                   "base_path": "snaps"}, "s3")
    finally:
        node.close()


def test_s3_rejects_plain_credentials(tmp_path, fixture_server):
    endpoint = fixture_server("s3")
    node = _node_with_keystore(tmp_path)
    try:
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/bad", None,
            {"type": "s3", "settings": {
                "bucket": "b", "endpoint": endpoint,
                "access_key": "LEAKED", "secret_key": "LEAKED"}})
        assert st == 400
        assert "keystore" in json.dumps(r)
    finally:
        node.close()


def test_s3_bad_signature_rejected(tmp_path, fixture_server):
    endpoint = fixture_server("s3")
    data = tmp_path / "d2"
    data.mkdir()
    ks = KeyStore.create(str(data / KEYSTORE_FILENAME), "")
    ks.set_string("s3.client.default.access_key", ACCESS)
    ks.set_string("s3.client.default.secret_key", "WRONG")
    ks.save("")
    node = Node(data_path=str(data))
    try:
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/cloud", None,
            {"type": "s3", "settings": {"bucket": "b1",
                                        "endpoint": endpoint}})
        assert st == 200
        st, r = node.rest_controller.dispatch(
            "PUT", "/_snapshot/cloud/snapx",
            {"wait_for_completion": "true"}, {})
        assert st >= 400    # signature mismatch surfaces as repo error
    finally:
        node.close()


def test_gcs_repository_roundtrip(tmp_path, fixture_server):
    endpoint = fixture_server("gcs")
    node = _node_with_keystore(tmp_path)
    try:
        _snapshot_roundtrip(node, {"bucket": "b2", "endpoint": endpoint},
                            "gcs")
    finally:
        node.close()


def test_azure_repository_roundtrip(tmp_path, fixture_server):
    endpoint = fixture_server("azure")
    node = _node_with_keystore(tmp_path)
    try:
        _snapshot_roundtrip(node, {"container": "c1",
                                   "endpoint": endpoint}, "azure")
    finally:
        node.close()
