"""Native C++ layer tests: build, parity with Python implementations
(model: the reference's native-integration seams are tested via their Java
wrappers; here parity tests are the contract)."""

import numpy as np
import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.analysis.tokenizers import StandardTokenizer

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_tokenizer_parity_ascii():
    py = StandardTokenizer()
    texts = [
        "The Quick-Brown Fox, jumped over 2 dogs!",
        "hello   world",
        "",
        "a",
        "trailing space ",
        " LEADING",
        "123 abc456def 789",
        "x" * 300 + " ok",  # over max_token_length -> dropped
    ]
    for text in texts:
        expected = [(t.term.lower(), t.start_offset, t.end_offset)
                    for t in py._tokenize_py(text)]
        got = native.tokenize_ascii(text)
        assert got == expected, (text, got, expected)


def test_analyzer_uses_native_path():
    reg = AnalysisRegistry()
    std = reg.get("standard")
    assert std.tokenizer.native_lowercase is True
    assert std.terms("Fast ASCII Path") == ["fast", "ascii", "path"]
    # non-ASCII falls back to the full-Unicode Python path
    assert std.terms("Crème brûlée") == ["crème", "brûlée"]


def test_varint_roundtrip():
    rng = np.random.default_rng(1)
    docids = np.sort(rng.choice(1_000_000, size=5000, replace=False)).astype(np.int32)
    data = native.varint_encode(docids)
    assert len(data) < docids.nbytes  # actually compresses sorted deltas
    out = native.varint_decode(data, len(docids))
    np.testing.assert_array_equal(out, docids)


def test_varint_empty_and_single():
    assert native.varint_decode(native.varint_encode(np.array([], np.int32)), 0).size == 0
    one = np.array([12345], np.int32)
    np.testing.assert_array_equal(
        native.varint_decode(native.varint_encode(one), 1), one)


def test_varint_detects_truncation():
    docids = np.arange(100, dtype=np.int32) * 1000
    data = native.varint_encode(docids)
    with pytest.raises(ValueError):
        native.varint_decode(data[:-2], 100)


def test_count_term_freqs_parity():
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 50, size=1000).astype(np.int32)
    terms, tfs = native.count_term_freqs(ids)
    expected_terms, expected_counts = np.unique(ids, return_counts=True)
    order = np.argsort(terms)
    np.testing.assert_array_equal(terms[order], expected_terms)
    np.testing.assert_array_equal(tfs[order].astype(int), expected_counts)


def _py_murmur3(key: str) -> int:
    """Pure-Python spec copy of Murmur3HashFunction (the oracle)."""
    import struct
    data = key.encode("utf-16-le")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = 0
    rounded = len(data) & ~0x3
    for i in range(0, rounded, 4):
        (k,) = struct.unpack_from("<i", data, i)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = len(data) & 0x3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h - 0x100000000 if h >= 0x80000000 else h


def test_routing_hash_matches_spec():
    """The routing hash (native fast path or Python fallback) must stay
    bit-exact with Murmur3HashFunction across key shapes."""
    import random
    import string
    from elasticsearch_tpu.index.service import murmur3_hash
    rng = random.Random(5)
    for _ in range(500):
        key = "".join(rng.choices(string.printable + "日本語éüß🙂",
                                  k=rng.randrange(0, 50)))
        assert murmur3_hash(key) == _py_murmur3(key), repr(key)
    assert murmur3_hash("") == 0


def test_maxscore_topk_matches_exact():
    """The C++ block-max MaxScore scorer returns the exact top-k a dense
    scorer computes (it prunes non-competitive docs, never competitive
    ones)."""
    import numpy as np

    from elasticsearch_tpu import native

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(17)
    n_docs = 5000
    k1, b = 1.2, 0.75
    lens = rng.integers(5, 80, size=n_docs).astype(np.float32)
    avg = float(lens.mean())
    norm = k1 * (1.0 - b + b * lens / avg)

    for trial in range(10):
        n_terms = int(rng.integers(1, 7))
        docs_l, sat_l, post_off, post_len = [], [], [], []
        blk_off, blk_len, idfs = [], [], []
        bmax_l = []
        exact = np.zeros(n_docs, np.float64)
        off = 0
        boff = 0
        for _ in range(n_terms):
            df = int(rng.integers(1, n_docs // 2))
            d = np.sort(rng.choice(n_docs, size=df, replace=False)).astype(np.int32)
            tf = rng.integers(1, 6, size=df).astype(np.float32)
            s = tf / (tf + norm[d])
            w = float(np.log(1 + (n_docs - df + 0.5) / (df + 0.5)))
            exact[d] += w * s
            # pad postings to 128-blocks (corpus layout)
            nb = (df + 127) // 128
            pd = np.zeros(nb * 128, np.int32)
            ps = np.zeros(nb * 128, np.float32)
            pd[:df] = d
            ps[:df] = s
            docs_l.append(pd)
            sat_l.append(ps)
            bmax_l.append(ps.reshape(nb, 128).max(axis=1))
            post_off.append(off)
            post_len.append(df)
            blk_off.append(boff)
            blk_len.append(nb)
            idfs.append(w)
            off += nb * 128
            boff += nb
        k = int(rng.integers(1, 50))
        res = native.maxscore_topk(
            np.concatenate(docs_l), np.concatenate(sat_l),
            np.concatenate(bmax_l), np.asarray(post_off),
            np.asarray(post_len), np.asarray(blk_off),
            np.asarray(blk_len), np.asarray(idfs, np.float32), k)
        assert res is not None
        scores, docs = res
        matched = np.nonzero(exact > 0)[0]
        order = matched[np.lexsort((matched, -exact[matched]))][:k]
        assert len(docs) == min(k, len(order))
        np.testing.assert_array_equal(docs, order.astype(np.int32))
        np.testing.assert_allclose(scores, exact[order].astype(np.float32),
                                   rtol=2e-5, atol=1e-6)
