"""Native C++ layer tests: build, parity with Python implementations
(model: the reference's native-integration seams are tested via their Java
wrappers; here parity tests are the contract)."""

import numpy as np
import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.analysis.tokenizers import StandardTokenizer

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_tokenizer_parity_ascii():
    py = StandardTokenizer()
    texts = [
        "The Quick-Brown Fox, jumped over 2 dogs!",
        "hello   world",
        "",
        "a",
        "trailing space ",
        " LEADING",
        "123 abc456def 789",
        "x" * 300 + " ok",  # over max_token_length -> dropped
    ]
    for text in texts:
        expected = [(t.term.lower(), t.start_offset, t.end_offset)
                    for t in py._tokenize_py(text)]
        got = native.tokenize_ascii(text)
        assert got == expected, (text, got, expected)


def test_analyzer_uses_native_path():
    reg = AnalysisRegistry()
    std = reg.get("standard")
    assert std.tokenizer.native_lowercase is True
    assert std.terms("Fast ASCII Path") == ["fast", "ascii", "path"]
    # non-ASCII falls back to the full-Unicode Python path
    assert std.terms("Crème brûlée") == ["crème", "brûlée"]


def test_varint_roundtrip():
    rng = np.random.default_rng(1)
    docids = np.sort(rng.choice(1_000_000, size=5000, replace=False)).astype(np.int32)
    data = native.varint_encode(docids)
    assert len(data) < docids.nbytes  # actually compresses sorted deltas
    out = native.varint_decode(data, len(docids))
    np.testing.assert_array_equal(out, docids)


def test_varint_empty_and_single():
    assert native.varint_decode(native.varint_encode(np.array([], np.int32)), 0).size == 0
    one = np.array([12345], np.int32)
    np.testing.assert_array_equal(
        native.varint_decode(native.varint_encode(one), 1), one)


def test_varint_detects_truncation():
    docids = np.arange(100, dtype=np.int32) * 1000
    data = native.varint_encode(docids)
    with pytest.raises(ValueError):
        native.varint_decode(data[:-2], 100)


def test_count_term_freqs_parity():
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 50, size=1000).astype(np.int32)
    terms, tfs = native.count_term_freqs(ids)
    expected_terms, expected_counts = np.unique(ids, return_counts=True)
    order = np.argsort(terms)
    np.testing.assert_array_equal(terms[order], expected_terms)
    np.testing.assert_array_equal(tfs[order].astype(int), expected_counts)


def _py_murmur3(key: str) -> int:
    """Pure-Python spec copy of Murmur3HashFunction (the oracle)."""
    import struct
    data = key.encode("utf-16-le")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = 0
    rounded = len(data) & ~0x3
    for i in range(0, rounded, 4):
        (k,) = struct.unpack_from("<i", data, i)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = len(data) & 0x3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h - 0x100000000 if h >= 0x80000000 else h


def test_routing_hash_matches_spec():
    """The routing hash (native fast path or Python fallback) must stay
    bit-exact with Murmur3HashFunction across key shapes."""
    import random
    import string
    from elasticsearch_tpu.index.service import murmur3_hash
    rng = random.Random(5)
    for _ in range(500):
        key = "".join(rng.choices(string.printable + "日本語éüß🙂",
                                  k=rng.randrange(0, 50)))
        assert murmur3_hash(key) == _py_murmur3(key), repr(key)
    assert murmur3_hash("") == 0
