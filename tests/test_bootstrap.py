"""Bootstrap checks + launcher (ref: bootstrap/BootstrapChecks.java,
Bootstrap.init): development mode warns, production mode (non-loopback
bind) fails hard; the `python -m elasticsearch_tpu` launcher starts a
node in an EXTERNAL process, serves HTTP, and stops cleanly on
SIGTERM."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from elasticsearch_tpu.common import bootstrap
from elasticsearch_tpu.common.settings import Settings

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_development_mode_warns_not_raises():
    # this environment is root with low limits: failures exist, but a
    # loopback bind only warns (ref: enforceLimits on non-loopback)
    failures = bootstrap.run_bootstrap_checks(
        Settings.EMPTY, bind_host="127.0.0.1")
    assert isinstance(failures, list)


def test_production_mode_enforces():
    settings = Settings.from_dict({"discovery": {"seed_hosts": "a:9300"}})
    checks_fail = bool(bootstrap.run_bootstrap_checks(
        settings, bind_host="127.0.0.1"))
    if not checks_fail:
        pytest.skip("environment satisfies every limit check")
    with pytest.raises(bootstrap.BootstrapCheckFailure,
                       match=r"bootstrap checks failed"):
        bootstrap.run_bootstrap_checks(settings, bind_host="0.0.0.0")


def test_discovery_configuration_check():
    msg = bootstrap.discovery_configuration_check(Settings.EMPTY)
    assert "discovery.seed_hosts" in msg
    ok = bootstrap.discovery_configuration_check(
        Settings.from_dict({"discovery": {"seed_hosts": "h:9300"}}))
    assert ok is None
    ok2 = bootstrap.discovery_configuration_check(
        Settings.from_dict({"cluster":
                            {"initial_master_nodes": ["n1"]}}))
    assert ok2 is None


def test_launcher_external_process(tmp_path):
    proc = subprocess.Popen(
        [sys.executable, "-m", "elasticsearch_tpu",
         "--data", str(tmp_path / "data"), "--quiet",
         "-E", "http.port=0", "-E", "http.native=false"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": REPO_ROOT,
             "JAX_PLATFORMS": "cpu"})
    try:
        # first import of jax in the child can take a while under a
        # loaded machine — wait for the startup line with a deadline
        import select
        deadline = time.time() + 420
        line = ""
        while time.time() < deadline:
            r, _, _ = select.select([proc.stdout], [], [], 5.0)
            if r:
                line = proc.stdout.readline()
                break
            if proc.poll() is not None:
                break
        assert line.startswith("started node="), (
            line, proc.poll(), proc.stderr.read() if proc.poll()
            is not None else "")
        port = int(line.rsplit("port=", 1)[1])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=30) as resp:
            root = json.loads(resp.read())
        assert root["tagline"]
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
