"""Sharded-search tests over the 8-device CPU mesh (model: the reference's
multi-node scatter-gather tests; validates collective merge == single-host
merge)."""

import jax
import numpy as np
import pytest

from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.segment import SegmentWriter
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.parallel.sharded import (
    ShardedIndex,
    build_sharded_index,
    make_mesh,
    sharded_bm25_topk,
    sharded_dfs_stats,
    sharded_knn_topk,
)

MAPPINGS = {"properties": {"body": {"type": "text"},
                           "vec": {"type": "dense_vector", "dims": 8,
                                   "similarity": "dot_product"}}}
VOCAB = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]


def build_shards(rng, n_shards=8, docs_per_shard=100, with_vec=True):
    svc = MapperService(mappings=MAPPINGS)
    segments = []
    all_docs = []
    probs = 1.0 / np.arange(1, len(VOCAB) + 1)
    probs /= probs.sum()
    for s in range(n_shards):
        w = SegmentWriter()
        for i in range(docs_per_shard):
            words = rng.choice(VOCAB, size=int(rng.integers(1, 20)), p=probs)
            doc = {"body": " ".join(words)}
            if with_vec:
                doc["vec"] = rng.standard_normal(8).astype(np.float32).tolist()
            w.add(svc.parse(f"{s}-{i}", doc))
            all_docs.append((s, i, doc))
        segments.append(w.build(f"shard{s}"))
    return segments, all_docs


@pytest.fixture(scope="module")
def sharded(rng=None):
    rng = np.random.default_rng(7)
    mesh = make_mesh(n_shards=8)
    segments, all_docs = build_shards(rng)
    index, pfs = build_sharded_index(mesh, segments, "body",
                                     with_vectors="vec")
    return mesh, segments, all_docs, index, pfs


def _select(pfs, index, terms, idfs):
    """Host-side block selection per shard, padded to a common NB."""
    per_shard = []
    for pf in pfs:
        ids, ws = [], []
        for t, w in zip(terms, idfs):
            tid = pf.term_id(t) if pf else -1
            if tid >= 0:
                start, cnt = int(pf.term_block_start[tid]), int(pf.term_block_count[tid])
                ids.extend(range(start, start + cnt))
                ws.extend([w] * cnt)
        per_shard.append((ids, ws))
    nb = max(8, max(len(i) for i, _ in per_shard))
    zero_block = index.block_docids.shape[1] - 1  # reserved zero pad row
    sel = np.full((len(pfs), nb), zero_block, np.int32)
    wsel = np.zeros((len(pfs), nb), np.float32)
    for s, (ids, ws) in enumerate(per_shard):
        sel[s, : len(ids)] = ids
        wsel[s, : len(ids)] = ws
    return sel, wsel


def test_sharded_bm25_matches_global_reference(sharded):
    mesh, segments, all_docs, index, pfs = sharded
    terms = ["alpha", "gamma"]
    # shard-level dfs -> global idf (the DFS phase)
    n_total = sum(pf.doc_count for pf in pfs)
    dfs = [sum(int(pf.doc_freq[pf.term_id(t)]) for pf in pfs
               if pf.term_id(t) >= 0) for t in terms]
    idfs = [bm25_ops.idf(df, n_total) for df in dfs]
    avg = index.avg_len

    sel, wsel = _select(pfs, index, terms, idfs)
    sel = np.broadcast_to(sel[:, None, :], (8, 1, sel.shape[1]))  # Q=1
    wsel = np.broadcast_to(wsel[:, None, :], (8, 1, wsel.shape[1]))
    vals, gids = sharded_bm25_topk(index, sel, wsel, k=10)
    vals, gids = np.asarray(vals)[0], np.asarray(gids)[0]

    # global scalar reference over all shards
    ref = {}
    for s, pf in enumerate(pfs):
        scores = bm25_ops.bm25_reference_scores(
            [pf.postings(t) for t in terms], idfs,
            np.maximum(pf.field_lengths, 1.0), avg, 1.2, 0.75)
        for d, sc in enumerate(scores):
            if sc > 0:
                ref[s * index.n_docs_padded + d] = sc
    expected = sorted(ref.items(), key=lambda kv: (-kv[1], kv[0]))[:10]
    got = [(int(g), float(v)) for v, g in zip(vals, gids)]
    assert [g for g, _ in got] == [g for g, _ in expected]
    np.testing.assert_allclose([v for _, v in got],
                               [v for _, v in expected], rtol=2e-5)


def test_sharded_knn_matches_reference(sharded):
    mesh, segments, all_docs, index, pfs = sharded
    rng = np.random.default_rng(3)
    queries = rng.standard_normal((2, 8)).astype(np.float32)
    vals, gids = sharded_knn_topk(index, queries, k=5)
    vals, gids = np.asarray(vals), np.asarray(gids)

    # reference: dot product over every stored vector
    for qi in range(2):
        ref = {}
        for s, seg in enumerate(segments):
            vv = seg.vectors["vec"]
            scores = vv.vectors @ queries[qi]
            for d in range(seg.n_docs):
                if vv.has_value[d]:
                    ref[s * index.n_docs_padded + d] = scores[d]
        expected = sorted(ref.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
        np.testing.assert_allclose(vals[qi], [v for _, v in expected],
                                   rtol=1e-4, atol=1e-5)
        assert gids[qi].tolist() == [g for g, _ in expected]


def test_sharded_dfs_psum(sharded):
    mesh, segments, all_docs, index, pfs = sharded
    term = "alpha"
    idf_dummy = [1.0]
    sel, _ = _select(pfs, index, [term], idf_dummy)
    dfs = np.asarray(sharded_dfs_stats(index, sel))
    total_df = sum(int(pf.doc_freq[pf.term_id(term)]) for pf in pfs
                   if pf.term_id(term) >= 0)
    assert int(dfs.sum()) == total_df


def test_mesh_shapes():
    mesh = make_mesh(n_shards=4, n_replicas=2)
    assert mesh.shape == {"replica": 2, "shard": 4}
    mesh8 = make_mesh()
    assert mesh8.shape["shard"] == 8


def test_sharded_hybrid_rrf_matches_host_fusion(sharded):
    """The on-mesh RRF fusion must equal host-side fusion of the two
    branches' global top-k lists (BASELINE config 5 at mesh scale)."""
    from elasticsearch_tpu.parallel.sharded import sharded_hybrid_rrf
    mesh, segments, all_docs, index, pfs = sharded
    terms = ["alpha", "gamma"]
    n_total = sum(pf.doc_count for pf in pfs)
    dfs = [sum(int(pf.doc_freq[pf.term_id(t)]) for pf in pfs
               if pf.term_id(t) >= 0) for t in terms]
    idfs = [bm25_ops.idf(df, n_total) for df in dfs]
    sel, wsel = _select(pfs, index, terms, idfs)
    sel = np.broadcast_to(sel[:, None, :], (8, 1, sel.shape[1]))
    wsel = np.broadcast_to(wsel[:, None, :], (8, 1, wsel.shape[1]))
    rng = np.random.default_rng(5)
    queries = rng.standard_normal((1, 8)).astype(np.float32)

    k = 10
    rrf_vals, rrf_gids = sharded_hybrid_rrf(index, sel, wsel, queries, k)
    rrf_vals, rrf_gids = np.asarray(rrf_vals)[0], np.asarray(rrf_gids)[0]

    # host fusion of the two independently computed global branch lists
    b_vals, b_gids = sharded_bm25_topk(index, sel, wsel, k=k)
    v_vals, v_gids = sharded_knn_topk(index, queries, k=k)
    scores = {}
    for rank, (val, g) in enumerate(zip(np.asarray(b_vals)[0],
                                        np.asarray(b_gids)[0])):
        if np.isfinite(val):
            scores[int(g)] = scores.get(int(g), 0.0) + 1 / (60 + rank + 1)
    for rank, (val, g) in enumerate(zip(np.asarray(v_vals)[0],
                                        np.asarray(v_gids)[0])):
        if np.isfinite(val):
            scores[int(g)] = scores.get(int(g), 0.0) + 1 / (60 + rank + 1)
    expected = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    got = [(int(g), float(v)) for v, g in zip(rrf_vals, rrf_gids)
           if np.isfinite(v)]
    assert len(got) == len(expected)
    np.testing.assert_allclose([v for _, v in got],
                               [v for _, v in expected], rtol=1e-6)
    # ids must agree at every rank whose score is UNAMBIGUOUS (distinct
    # from its neighbors); tied scores may order ids differently
    exp_scores = [v for _, v in expected]
    for i, ((gg, gv), (eg, ev)) in enumerate(zip(got, expected)):
        ambiguous = (
            (i > 0 and abs(exp_scores[i - 1] - ev) < 1e-12)
            or (i + 1 < len(exp_scores)
                and abs(exp_scores[i + 1] - ev) < 1e-12))
        if not ambiguous:
            assert gg == eg, (i, got, expected)


def test_sharded_hybrid_rrf_replica_mesh(sharded):
    """Replica-axis query partitioning: a 4-shard x 2-replica mesh must
    produce the same fused results as the 8-shard mesh path computes for
    the corresponding corpus (smoke: executes and returns sane shapes)."""
    from elasticsearch_tpu.parallel.sharded import (ShardedIndex,
                                                    build_sharded_index,
                                                    make_mesh,
                                                    sharded_hybrid_rrf)
    rng = np.random.default_rng(11)
    mesh = make_mesh(n_shards=4, n_replicas=2)
    segments, _docs = build_shards(rng, n_shards=4, docs_per_shard=50)
    index, pfs = build_sharded_index(mesh, segments, "body",
                                     with_vectors="vec")
    terms = ["alpha"]
    idfs = [1.0]
    sel, wsel = _select(pfs, index, terms, idfs)
    # Q=2 so the batch splits evenly over the 2 replicas
    sel = np.broadcast_to(sel[:, None, :], (4, 2, sel.shape[1]))
    wsel = np.broadcast_to(wsel[:, None, :], (4, 2, wsel.shape[1]))
    queries = rng.standard_normal((2, 8)).astype(np.float32)
    vals, gids = sharded_hybrid_rrf(index, sel, wsel, queries, k=5)
    vals, gids = np.asarray(vals), np.asarray(gids)
    assert vals.shape == (2, 5) and gids.shape == (2, 5)
    assert np.isfinite(vals).any()
    # both queries used the same BM25 selection → same doc SETS from the
    # bm25 branch; scores include per-query knn so values differ
    assert (vals[0] > 0).any() and (vals[1] > 0).any()


# ---------------------------------------------------------------------------
# int32 global-id overflow: log-and-fall-back (satellite — with x64 off,
# shard * nd past 2^31 must merge host-side in int64, never wrap)
# ---------------------------------------------------------------------------

def _bm25_global_reference(pfs, index, terms, idfs, k):
    ref = {}
    for s, pf in enumerate(pfs):
        scores = bm25_ops.bm25_reference_scores(
            [pf.postings(t) for t in terms], idfs,
            np.maximum(pf.field_lengths, 1.0), index.avg_len, 1.2, 0.75)
        for d, sc in enumerate(scores):
            if sc > 0:
                ref[s * index.n_docs_padded + d] = sc
    return sorted(ref.items(), key=lambda kv: (-kv[1], kv[0]))[:k]


def test_sharded_bm25_gid_overflow_host_fallback(sharded, monkeypatch):
    import elasticsearch_tpu.parallel.sharded as sharded_mod
    mesh, segments, all_docs, index, pfs = sharded
    terms = ["alpha", "gamma"]
    n_total = sum(pf.doc_count for pf in pfs)
    dfs = [sum(int(pf.doc_freq[pf.term_id(t)]) for pf in pfs
               if pf.term_id(t) >= 0) for t in terms]
    idfs = [bm25_ops.idf(df, n_total) for df in dfs]
    sel, wsel = _select(pfs, index, terms, idfs)
    sel = np.broadcast_to(sel[:, None, :], (8, 1, sel.shape[1]))
    wsel = np.broadcast_to(wsel[:, None, :], (8, 1, wsel.shape[1]))
    # force the guard: every layout now "exceeds" int32 global ids
    monkeypatch.setattr(sharded_mod, "GID_INT32_LIMIT", 1)
    vals, gids = sharded_bm25_topk(index, sel, wsel, k=10)
    vals, gids = np.asarray(vals)[0], np.asarray(gids)[0]
    assert gids.dtype == np.int64
    expected = _bm25_global_reference(pfs, index, terms, idfs, 10)
    assert gids.tolist() == [g for g, _ in expected]
    np.testing.assert_allclose(vals, [v for _, v in expected], rtol=2e-5)


def test_sharded_knn_gid_overflow_host_fallback(sharded, monkeypatch):
    import elasticsearch_tpu.parallel.sharded as sharded_mod
    mesh, segments, all_docs, index, pfs = sharded
    rng = np.random.default_rng(3)
    queries = rng.standard_normal((2, 8)).astype(np.float32)
    monkeypatch.setattr(sharded_mod, "GID_INT32_LIMIT", 1)
    vals, gids = sharded_knn_topk(index, queries, k=5)
    vals, gids = np.asarray(vals), np.asarray(gids)
    assert gids.dtype == np.int64
    for qi in range(2):
        ref = {}
        for s, seg in enumerate(segments):
            vv = seg.vectors["vec"]
            scores = vv.vectors @ queries[qi]
            for d in range(seg.n_docs):
                if vv.has_value[d]:
                    ref[s * index.n_docs_padded + d] = scores[d]
        expected = sorted(ref.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
        np.testing.assert_allclose(vals[qi], [v for _, v in expected],
                                   rtol=1e-4, atol=1e-5)
        assert gids[qi].tolist() == [g for g, _ in expected]
