"""JDBC-analogue driver: DB-API 2.0 over `/_sql?mode=jdbc` with binary
(CBOR) communication (ref: x-pack/plugin/sql/jdbc — JdbcHttpClient
builds Mode.JDBC requests with binaryCommunication; DefaultCursor pages;
TypeConverter maps wire values)."""

import datetime as dt

import pytest

from elasticsearch_tpu.client import dbapi
from elasticsearch_tpu.common import cbor
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

# ---------------------------------------------------------------------------
# CBOR codec
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("value", [
    None, True, False, 0, 23, 24, 255, 256, 65535, 65536, 2**32, -1, -25,
    -2**40, 1.5, -0.25, "", "héllo", "a" * 300, b"", b"\x00\xff" * 40,
    [], [1, [2, "three"], None], {}, {"a": 1, "b": [True, {"c": -2.5}]},
])
def test_cbor_roundtrip(value):
    assert cbor.loads(cbor.dumps(value)) == value


def test_cbor_wire_format_pins():
    # RFC 7049 test vectors
    assert cbor.dumps(0) == b"\x00"
    assert cbor.dumps(23) == b"\x17"
    assert cbor.dumps(24) == b"\x18\x18"
    assert cbor.dumps(-1) == b"\x20"
    assert cbor.dumps("a") == b"\x61a"
    assert cbor.dumps([1, 2]) == b"\x82\x01\x02"
    assert cbor.dumps(1.5) == b"\xfb\x3f\xf8\x00\x00\x00\x00\x00\x00"
    assert cbor.loads(b"\xf9\x3c\x00") == 1.0          # half float decode
    assert cbor.loads(b"\xfa\x3f\xc0\x00\x00") == 1.5  # single float decode
    # indefinite-length array + string from a foreign encoder
    assert cbor.loads(b"\x9f\x01\x02\xff") == [1, 2]
    assert cbor.loads(b"\x7f\x61a\x61b\xff") == "ab"


def test_cbor_errors():
    with pytest.raises(ValueError):
        cbor.loads(b"\x18")          # truncated
    with pytest.raises(ValueError):
        cbor.loads(b"\x00\x00")      # trailing bytes
    with pytest.raises(ValueError):
        cbor.loads(b"\x81" * 2000 + b"\x00")   # nesting bomb → bounded
    with pytest.raises(ValueError):
        cbor.loads(b"\xa1\x80\x00")  # array as map key → decode error
    # 64-bit overflow encodes as a decimal string, not a crash
    assert cbor.loads(cbor.dumps(2**70)) == str(2**70)
    assert cbor.loads(cbor.dumps(-2**70)) == str(-2**70)
    assert cbor.loads(cbor.dumps(2**64 - 1)) == 2**64 - 1


# ---------------------------------------------------------------------------
# driver end-to-end
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(settings=Settings.from_dict({"http": {"native": False}}),
             data_path=str(tmp_path_factory.mktemp("jdbc") / "data"))
    port = n.start(0)
    c = n.rest_controller
    c.dispatch("PUT", "/library", {}, {
        "mappings": {"properties": {
            "title": {"type": "keyword"},
            "pages": {"type": "integer"},
            "price": {"type": "double"},
            "published": {"type": "date"},
            "in_print": {"type": "boolean"}}}})
    books = [
        ("Leviathan Wakes", 561, 9.99, "2011-06-02T00:00:00Z", True),
        ("Hyperion", 482, 7.50, "1989-05-26T00:00:00Z", True),
        ("Dune", 604, 11.25, "1965-08-01T00:00:00Z", True),
        ("The Left Hand of Darkness", 304, 6.99,
         "1969-03-01T00:00:00Z", False),
        ("Neuromancer", 271, 8.25, "1984-07-01T00:00:00Z", True),
    ]
    for i, (t, pg, pr, pub, ip) in enumerate(books):
        c.dispatch("PUT", f"/library/_doc/{i}", {}, {
            "title": t, "pages": pg, "price": pr, "published": pub,
            "in_print": ip})
    c.dispatch("POST", "/library/_refresh", {}, None)
    yield n, port
    n.close()


@pytest.fixture(scope="module")
def conn(node):
    _, port = node
    con = dbapi.connect(f"jdbc:es://127.0.0.1:{port}/")
    yield con
    con.close()


def test_connect_checks_server(node):
    _, port = node
    con = dbapi.connect(host="127.0.0.1", port=port)
    assert "version" in con.server_info
    assert con.ping()
    con.close()
    with pytest.raises(dbapi.InterfaceError):
        con.cursor().execute("SELECT 1")
    # connection refused → OperationalError at connect
    with pytest.raises(dbapi.OperationalError):
        dbapi.connect(host="127.0.0.1", port=1, timeout=2)


def test_select_description_and_types(conn):
    cur = conn.cursor()
    cur.execute("SELECT title, pages, price, published, in_print "
                "FROM library ORDER BY pages DESC")
    names = [d[0] for d in cur.description]
    assert names == ["title", "pages", "price", "published", "in_print"]
    codes = [d[1] for d in cur.description]
    assert codes == [dbapi.STRING, dbapi.NUMBER, dbapi.NUMBER,
                     dbapi.DATETIME, dbapi.BOOLEAN]
    # display_size flows from the server's JDBC-mode column metadata
    # (ref: SqlDataTypes.displaySize — keyword 32766, integer 11)
    assert cur.description[0][2] == 32766
    assert cur.description[1][2] == 11
    rows = cur.fetchall()
    assert [r[0] for r in rows[:2]] == ["Dune", "Leviathan Wakes"]
    assert isinstance(rows[0][3], dt.datetime)       # TypeConverter parity
    assert rows[0][4] is True
    cur.close()


def test_qmark_parameters_typed(conn):
    cur = conn.cursor()
    cur.execute("SELECT title FROM library WHERE pages > ? AND price < ? "
                "ORDER BY title ASC", (400, 10.0))
    assert [r[0] for r in cur.fetchall()] == ["Hyperion", "Leviathan Wakes"]
    # strings quote-escape through the typed-param path
    cur.execute("SELECT pages FROM library WHERE title = ?", ("Dune",))
    assert cur.fetchone() == [604]
    assert cur.fetchone() is None
    # ? inside a string literal is NOT a parameter
    cur.execute("SELECT title FROM library WHERE title = '?' OR pages = ?",
                (271,))
    assert [r[0] for r in cur.fetchall()] == ["Neuromancer"]
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("SELECT title FROM library WHERE pages > ?", ())


def test_cursor_paging_small_pages(node):
    _, port = node
    con = dbapi.connect(host="127.0.0.1", port=port, page_size=2)
    cur = con.cursor()
    cur.execute("SELECT title FROM library ORDER BY title ASC")
    titles = [r[0] for r in cur]       # iterator protocol drains all pages
    assert titles == sorted(titles)
    assert len(titles) == 5
    con.close()


def test_aggregates_and_constant_select(conn):
    cur = conn.cursor()
    cur.execute("SELECT COUNT(*) AS n, AVG(pages) AS avg_pages FROM library")
    n, avg_pages = cur.fetchone()
    assert n == 5
    assert abs(avg_pages - (561 + 482 + 604 + 304 + 271) / 5) < 1e-6
    cur.execute("SELECT 1 + 1")
    assert cur.fetchone() == [2]


def test_json_mode_fallback(node):
    _, port = node
    con = dbapi.connect(host="127.0.0.1", port=port, binary=False)
    cur = con.cursor()
    cur.execute("SELECT title FROM library WHERE in_print = ?", (False,))
    assert cur.fetchall() == [["The Left Hand of Darkness"]]
    con.close()


def test_mode_in_url_only(node):
    """mode=jdbc in the URL alone must produce display_size columns
    (ref: RestSqlQueryAction — mode is a request parameter)."""
    n, _ = node
    status, r = n.rest_controller.dispatch(
        "POST", "/_sql", {"mode": "jdbc"},
        {"query": "SELECT title FROM library LIMIT 1"})
    assert status == 200
    assert r["columns"][0]["display_size"] == 32766


def test_non_finite_param_rejected(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("SELECT title FROM library WHERE price > ?",
                    (float("nan"),))


def test_driver_against_native_front(tmp_path_factory):
    """The C++ epoll front negotiates CBOR the same way the stdlib
    server does (rest/native_http.py mirrors http_server.py)."""
    n = Node(settings=Settings.from_dict({"http": {"native": "auto"}}),
             data_path=str(tmp_path_factory.mktemp("jn") / "data"))
    try:
        port = n.start(0)
        if not type(n._http).__name__.startswith("Native"):
            pytest.skip("native front unavailable on this host")
        c = n.rest_controller
        c.dispatch("PUT", "/nf", None, {"mappings": {"properties": {
            "v": {"type": "integer"}}}})
        for i in range(5):
            c.dispatch("PUT", f"/nf/_doc/{i}", None, {"v": i})
        c.dispatch("POST", "/nf/_refresh", None, None)
        con = dbapi.connect(host="127.0.0.1", port=port)
        cur = con.cursor()
        cur.execute("SELECT v FROM nf WHERE v >= ? ORDER BY v ASC", (3,))
        assert cur.fetchall() == [[3], [4]]
        con.close()
    finally:
        n.close()


def test_errors_surface_as_programming_errors(conn):
    cur = conn.cursor()
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("SELEKT nope")
    with pytest.raises(dbapi.NotSupportedError):
        conn.rollback()
    conn.commit()    # auto-commit no-op
