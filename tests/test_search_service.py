"""Search service tests: query-then-fetch over multi-shard indices, sort,
pagination, scroll, highlight, rank_eval (model: the reference's
SearchServiceTests + SearchPhaseControllerTests + rank-eval tests)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    SearchContextMissingException,
)
from elasticsearch_tpu.index.service import IndicesService, murmur3_hash
from elasticsearch_tpu.search.rank_eval import rank_eval
from elasticsearch_tpu.search.service import SearchService

MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "tag": {"type": "keyword"},
        "views": {"type": "long"},
    }
}


@pytest.fixture
def services(tmp_path):
    indices = IndicesService(str(tmp_path / "data"))
    search = SearchService(indices)
    yield indices, search
    indices.close()


def fill(indices, name="test", num_shards=2, n=20):
    idx = indices.create_index(name, {"index.number_of_shards": num_shards},
                               MAPPINGS)
    for i in range(n):
        idx.index_doc(str(i), {
            "title": f"doc number {i} " + ("quick fox " * (i % 3)),
            "tag": "even" if i % 2 == 0 else "odd",
            "views": i,
        })
    idx.refresh()
    return idx


def _signed(x):
    return x - 0x100000000 if x >= 0x80000000 else x


def test_murmur3_matches_java_reference():
    # known vectors from the reference's Murmur3HashFunctionTests.java
    assert murmur3_hash("hell") == _signed(0x5A0CB7C3)
    assert murmur3_hash("hello") == _signed(0xD7C31989)
    assert murmur3_hash("hello w") == _signed(0x22AB2984)
    assert murmur3_hash("hello wo") == _signed(0xDF0CA123)
    assert murmur3_hash("hello wor") == _signed(0xE7744D61)
    assert murmur3_hash("The quick brown fox jumps over the lazy dog") == _signed(0xE07DB09C)
    assert murmur3_hash("The quick brown fox jumps over the lazy cog") == _signed(0x4E63D2AD)


def test_basic_search(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {"query": {"match": {"title": "quick fox"}}})
    assert r["hits"]["total"]["value"] == 13  # i%3 != 0 → 13 of 20
    assert len(r["hits"]["hits"]) == 10  # default size
    assert r["hits"]["max_score"] > 0
    top = r["hits"]["hits"][0]
    assert top["_index"] == "test"
    assert "quick fox quick fox" in top["_source"]["title"]
    assert r["_shards"]["total"] == 2


def test_match_all_default(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {})
    assert r["hits"]["total"]["value"] == 20


def test_from_size_pagination_is_stable(services):
    indices, search = services
    fill(indices)
    body = {"query": {"match_all": {}}, "sort": [{"views": "asc"}]}
    seen = []
    for frm in range(0, 20, 5):
        r = search.search("test", {**body, "from": frm, "size": 5})
        seen.extend(h["_source"]["views"] for h in r["hits"]["hits"])
    assert seen == list(range(20))


def test_sort_desc_and_sort_values(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {"sort": [{"views": {"order": "desc"}}], "size": 3})
    views = [h["_source"]["views"] for h in r["hits"]["hits"]]
    assert views == [19, 18, 17]
    assert r["hits"]["hits"][0]["sort"] == [19.0]
    assert r["hits"]["max_score"] is None  # no scores when sorting by field


def test_search_after(services):
    indices, search = services
    fill(indices)
    body = {"sort": [{"views": "asc"}], "size": 5}
    r = search.search("test", body)
    last = r["hits"]["hits"][-1]["sort"]
    r2 = search.search("test", {**body, "search_after": last})
    assert [h["_source"]["views"] for h in r2["hits"]["hits"]] == [5, 6, 7, 8, 9]


def test_post_filter_and_min_score(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {
        "query": {"match": {"title": "quick"}},
        "post_filter": {"term": {"tag": "even"}},
    })
    assert all(h["_source"]["tag"] == "even" for h in r["hits"]["hits"])
    r_all = search.search("test", {"query": {"match": {"title": "quick"}}})
    r_min = search.search("test", {"query": {"match": {"title": "quick"}},
                                   "min_score": r_all["hits"]["max_score"] - 1e-6})
    assert r_min["hits"]["total"]["value"] <= r_all["hits"]["total"]["value"]


def test_source_filtering(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {"_source": ["views"], "size": 1})
    assert set(r["hits"]["hits"][0]["_source"].keys()) == {"views"}
    r2 = search.search("test", {"_source": False, "size": 1})
    assert "_source" not in r2["hits"]["hits"][0]


def test_docvalue_fields(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {"docvalue_fields": ["views", "tag"], "size": 1,
                               "sort": [{"views": "asc"}]})
    fields = r["hits"]["hits"][0]["fields"]
    assert fields["views"] == [0.0]
    assert fields["tag"] == ["even"]


def test_multi_index_and_wildcards(services):
    indices, search = services
    fill(indices, "logs-1", n=5)
    fill(indices, "logs-2", n=5)
    fill(indices, "other", n=5)
    r = search.search("logs-*", {"size": 20})
    assert r["hits"]["total"]["value"] == 10
    assert {h["_index"] for h in r["hits"]["hits"]} == {"logs-1", "logs-2"}
    r_all = search.search("_all", {"size": 30})
    assert r_all["hits"]["total"]["value"] == 15


def test_scroll_pages_through_everything(services):
    indices, search = services
    fill(indices, n=17)
    r = search.search("test", {"sort": [{"views": "asc"}], "size": 5},
                      scroll="1m")
    collected = [h["_source"]["views"] for h in r["hits"]["hits"]]
    sid = r["_scroll_id"]
    while True:
        r = search.scroll(sid, scroll="1m")
        hits = r["hits"]["hits"]
        if not hits:
            break
        collected.extend(h["_source"]["views"] for h in hits)
    assert collected == list(range(17))
    assert search.clear_scroll([sid]) == 1
    with pytest.raises(SearchContextMissingException):
        search.scroll(sid)


def test_scroll_by_score(services):
    indices, search = services
    fill(indices, n=12)
    r = search.search("test", {"query": {"match": {"title": "doc"}}, "size": 4},
                      scroll="1m")
    sid = r["_scroll_id"]
    ids = [h["_id"] for h in r["hits"]["hits"]]
    while True:
        r = search.scroll(sid)
        if not r["hits"]["hits"]:
            break
        ids.extend(h["_id"] for h in r["hits"]["hits"])
    assert len(ids) == 12
    assert len(set(ids)) == 12  # no dup, no loss across equal scores


def test_result_window_guard(services):
    indices, search = services
    fill(indices)
    with pytest.raises(IllegalArgumentException):
        search.search("test", {"from": 9995, "size": 10})


def test_count(services):
    indices, search = services
    fill(indices)
    r = search.count("test", {"query": {"term": {"tag": "even"}}})
    assert r["count"] == 10


def test_highlight(services):
    indices, search = services
    fill(indices)
    r = search.search("test", {
        "query": {"match": {"title": "quick"}},
        "highlight": {"fields": {"title": {}}},
        "size": 1,
    })
    frag = r["hits"]["hits"][0]["highlight"]["title"][0]
    assert "<em>quick</em>" in frag


def test_shard_routing_distributes(services):
    indices, _ = services
    idx = fill(indices, "dist", num_shards=4, n=100)
    counts = [s.stats()["docs"]["count"] for s in idx.shards]
    assert sum(counts) == 100
    assert all(c > 5 for c in counts)  # roughly balanced


def test_index_persistence_reopen(tmp_path):
    indices = IndicesService(str(tmp_path / "data"))
    idx = indices.create_index("persist", {}, MAPPINGS)
    idx.index_doc("1", {"title": "hello world"})
    idx.flush()
    indices.close()

    indices2 = IndicesService(str(tmp_path / "data"))
    search = SearchService(indices2)
    r = search.search("persist", {"query": {"match": {"title": "hello"}}})
    assert r["hits"]["total"]["value"] == 1
    indices2.close()


def test_create_duplicate_and_invalid(services):
    indices, _ = services
    indices.create_index("a", {}, {})
    with pytest.raises(ResourceAlreadyExistsException):
        indices.create_index("a", {}, {})
    with pytest.raises(IllegalArgumentException):
        indices.create_index("_bad", {}, {})


def test_rank_eval_metrics(services):
    indices, search = services
    fill(indices)

    def search_fn(body):
        r = search.search("test", {**body, "size": 10})
        return [h["_id"] for h in r["hits"]["hits"]]

    result = rank_eval(
        search_fn,
        [{"id": "q1",
          "request": {"query": {"match": {"title": "quick fox"}}},
          "ratings": [{"_id": "2", "rating": 1}, {"_id": "5", "rating": 1},
                      {"_id": "8", "rating": 1}]}],
        {"recall": {"k": 10}})
    assert 0.0 <= result["metric_score"] <= 1.0
    assert result["details"]["q1"]["metric_score"] == result["metric_score"]
    # all three rated docs match the query (i%3 in {2}), recall should be 1
    assert result["metric_score"] == 1.0


def test_rank_eval_precision_mrr_dcg():
    hits = ["a", "b", "c", "d"]

    def fn(body):
        return hits

    reqs = [{"id": "q", "request": {},
             "ratings": [{"_id": "b", "rating": 3}, {"_id": "d", "rating": 1}]}]
    assert rank_eval(fn, reqs, {"precision": {"k": 4}})["metric_score"] == 0.5
    assert rank_eval(fn, reqs, {"mean_reciprocal_rank": {}})["metric_score"] == 0.5
    import math
    expected_dcg = 7 / math.log2(3) + 1 / math.log2(5)
    assert rank_eval(fn, reqs, {"dcg": {"k": 4}})["metric_score"] == pytest.approx(expected_dcg)
    ndcg = rank_eval(fn, reqs, {"dcg": {"k": 4, "normalize": True}})["metric_score"]
    ideal = 7 / math.log2(2) + 1 / math.log2(3)
    assert ndcg == pytest.approx(expected_dcg / ideal)


def test_shard_request_cache(tmp_path):
    """size=0 responses cache per (shard epochs, body); refresh after a
    write naturally invalidates (ref: IndicesRequestCache keyed by
    reader + request bytes)."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    indices = IndicesService(str(tmp_path / "rc"))
    idx = indices.create_index("rc", {}, {"properties": {
        "v": {"type": "long"}}})
    idx.index_doc("1", {"v": 1})
    idx.refresh()
    svc = SearchService(indices)
    body = {"size": 0, "track_total_hits": True,
            "aggs": {"s": {"sum": {"field": "v"}}}}
    r1 = svc.search("rc", body)
    assert svc.request_cache_stats == {"hit_count": 0, "miss_count": 1}
    r2 = svc.search("rc", body)
    assert svc.request_cache_stats["hit_count"] == 1
    assert r2["aggregations"] == r1["aggregations"]
    # a refresh-visible write changes the epoch → miss + fresh result
    idx.index_doc("2", {"v": 5})
    idx.refresh()
    r3 = svc.search("rc", body)
    assert svc.request_cache_stats["miss_count"] == 2
    assert r3["aggregations"]["s"]["value"] == 6.0
    # sized requests and request_cache:false bypass the cache entirely
    svc.search("rc", {"size": 1})
    svc.search("rc", {**body, "request_cache": False})
    assert svc.request_cache_stats == {"hit_count": 1, "miss_count": 2}
    # cached responses are isolated from caller mutation
    r2["aggregations"]["s"]["value"] = -1
    r4 = svc.search("rc", {"size": 0, "track_total_hits": True,
                           "aggs": {"s": {"sum": {"field": "v"}}}})
    assert r4["aggregations"]["s"]["value"] == 6.0
    indices.close()


def test_request_cache_index_recreation_isolated(tmp_path):
    """Deleting and recreating an index with identical epochs must not
    serve the old index's cached responses (identity in the key)."""
    from elasticsearch_tpu.index.service import IndicesService
    from elasticsearch_tpu.search.service import SearchService
    import time as _time
    indices = IndicesService(str(tmp_path / "rcid"))
    body = {"size": 0, "track_total_hits": True,
            "aggs": {"s": {"sum": {"field": "v"}}}}

    def make(v):
        idx = indices.create_index("rc", {}, {"properties": {
            "v": {"type": "long"}}})
        idx.index_doc("1", {"v": v})
        idx.refresh()
        return idx

    make(1)
    svc = SearchService(indices)
    r1 = svc.search("rc", body)
    assert r1["aggregations"]["s"]["value"] == 1.0
    indices.delete_index("rc")
    _time.sleep(0.002)                       # distinct creation_date ms
    make(5)
    r2 = svc.search("rc", body)
    assert r2["aggregations"]["s"]["value"] == 5.0
    indices.close()
