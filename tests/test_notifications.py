"""Round-5 notification surfaces: watcher email/slack/pagerduty actions
(ref: x-pack/plugin/watcher/.../actions/email/EmailAction.java:30 and
siblings), the monitoring HTTP exporter (ref: monitoring/.../exporter/
http/HttpExporter.java:80), and the ML inference ingest processor
(ref: ml/.../inference/ingest/InferenceProcessor.java:59).

Email delivery is proven against an in-process SMTP fixture; slack and
pagerduty against an in-process HTTP fixture (the zero-egress delivery
policy posts only to loopback); the HTTP exporter round-trips into a
second REAL node's .monitoring-es index.
"""

import json
import socketserver
import threading
import time

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node


def call(node, method, path, body=None, expect=(200, 201), **params):
    status, r = node.rest_controller.dispatch(method, path, params, body)
    ok = (status in expect) if isinstance(expect, tuple) else \
        status == expect
    assert ok, (status, r)
    return r


# --------------------------------------------------------------- fixtures

class _SmtpHandler(socketserver.StreamRequestHandler):
    def handle(self):
        srv = self.server
        self.wfile.write(b"220 fixture ESMTP\r\n")
        sender, rcpts, data = None, [], None
        while True:
            line = self.rfile.readline()
            if not line:
                return
            cmd = line.decode(errors="replace").strip()
            up = cmd.upper()
            if up.startswith(("HELO", "EHLO")):
                self.wfile.write(b"250 fixture\r\n")
            elif up.startswith("MAIL FROM:"):
                sender = cmd[10:].strip().strip("<>")
                self.wfile.write(b"250 OK\r\n")
            elif up.startswith("RCPT TO:"):
                rcpts.append(cmd[8:].strip().strip("<>"))
                self.wfile.write(b"250 OK\r\n")
            elif up == "DATA":
                self.wfile.write(b"354 go\r\n")
                lines = []
                while True:
                    dl = self.rfile.readline()
                    if dl.rstrip(b"\r\n") == b".":
                        break
                    lines.append(dl)
                data = b"".join(lines).decode(errors="replace")
                srv.messages.append(
                    {"from": sender, "to": list(rcpts), "data": data})
                sender, rcpts = None, []
                self.wfile.write(b"250 delivered\r\n")
            elif up == "QUIT":
                self.wfile.write(b"221 bye\r\n")
                return
            else:
                self.wfile.write(b"250 OK\r\n")


@pytest.fixture()
def smtp_fixture():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _SmtpHandler)
    srv.messages = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


class _HttpCapture(socketserver.StreamRequestHandler):
    def handle(self):
        req = self.rfile.readline().decode()
        headers = {}
        while True:
            line = self.rfile.readline().decode().strip()
            if not line:
                break
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0))
        body = self.rfile.read(length).decode() if length else ""
        self.server.requests.append(
            {"line": req.strip(), "headers": headers, "body": body})
        resp = b'{"ok":true}'
        self.wfile.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
            b"Content-Length: " + str(len(resp)).encode() +
            b"\r\nConnection: close\r\n\r\n" + resp)


@pytest.fixture()
def http_fixture():
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), _HttpCapture)
    srv.requests = []
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _seed_errors(node):
    node.indices_service.create_index("logs", {}, {
        "properties": {"level": {"type": "keyword"}}})
    idx = node.indices_service.get("logs")
    for i in range(3):
        idx.index_doc(f"e{i}", {"level": "error"})
    idx.refresh()


WATCH_BASE = {
    "trigger": {"schedule": {"interval": "10m"}},
    "input": {"search": {"request": {
        "indices": ["logs"],
        "body": {"query": {"term": {"level": {"value": "error"}}},
                 "size": 0, "track_total_hits": True}}}},
    "condition": {"compare": {"payload.hits.total.value": {"gte": 1}}},
}


# ------------------------------------------------------------ email action

def test_email_action_delivers_via_smtp(tmp_path, smtp_fixture):
    host, port = smtp_fixture.server_address
    node = Node(settings=Settings.from_dict({
        "xpack": {"notification": {"email": {"account": {"main": {
            "smtp": {"host": host, "port": port},
            "email_defaults": {"from": "alerts@estpu.local"},
        }}}}}}), data_path=str(tmp_path / "n"))
    try:
        _seed_errors(node)
        watch = dict(WATCH_BASE)
        watch["actions"] = {"mail": {"email": {
            "to": ["ops@example.com"],
            "subject": "{{ctx.payload.hits.total.value}} errors found",
            "body": {"text": "watch {{ctx.watch_id}} fired"},
            "attachments": {"payload.json": {"data": {"format": "json"}}},
        }}}
        call(node, "PUT", "/_watcher/watch/errmail", watch)
        r = call(node, "POST", "/_watcher/watch/errmail/_execute")
        actions = r["watch_record"]["result"]["actions"]
        assert actions[0]["status"] == "success", actions
        deadline = time.time() + 5
        while not smtp_fixture.messages and time.time() < deadline:
            time.sleep(0.05)
        assert len(smtp_fixture.messages) == 1
        msg = smtp_fixture.messages[0]
        assert msg["from"] == "alerts@estpu.local"
        assert msg["to"] == ["ops@example.com"]
        assert "3 errors found" in msg["data"]        # rendered subject
        assert "watch errmail fired" in msg["data"]   # rendered body
        assert "payload.json" in msg["data"]          # attachment
    finally:
        node.close()


def test_email_action_without_account_renders(tmp_path):
    node = Node(data_path=str(tmp_path / "n"))
    try:
        _seed_errors(node)
        watch = dict(WATCH_BASE)
        watch["actions"] = {"mail": {"email": {
            "to": "ops@example.com", "subject": "s", "body": "b"}}}
        call(node, "PUT", "/_watcher/watch/w1", watch)
        r = call(node, "POST", "/_watcher/watch/w1/_execute")
        assert r["watch_record"]["result"]["actions"][0]["status"] == \
            "simulated"
        notes = node.watcher_service.notifications
        assert notes and notes[-1]["type"] == "email"
    finally:
        node.close()


# ----------------------------------------------------- slack / pagerduty

def test_slack_action_posts_to_webhook(tmp_path, http_fixture):
    host, port = http_fixture.server_address
    node = Node(settings=Settings.from_dict({
        "xpack": {"notification": {"slack": {"account": {"ops": {
            "secure_url": f"http://{host}:{port}/hook"}}}}},
    }), data_path=str(tmp_path / "n"))
    try:
        _seed_errors(node)
        watch = dict(WATCH_BASE)
        watch["actions"] = {"ping": {"slack": {"message": {
            "from": "watcher", "to": ["#ops"],
            "text": "{{ctx.payload.hits.total.value}} errors"}}}}
        call(node, "PUT", "/_watcher/watch/ws", watch)
        r = call(node, "POST", "/_watcher/watch/ws/_execute")
        assert r["watch_record"]["result"]["actions"][0]["status"] == \
            "success"
        assert len(http_fixture.requests) == 1
        payload = json.loads(http_fixture.requests[0]["body"])
        assert payload["text"] == "3 errors"
        assert payload["channel"] == ["#ops"]
    finally:
        node.close()


def test_pagerduty_action_posts_event(tmp_path, http_fixture):
    host, port = http_fixture.server_address
    node = Node(settings=Settings.from_dict({
        "xpack": {"notification": {"pagerduty": {"account": {"pd": {
            "service_api_key": "sekrit",
            "url": f"http://{host}:{port}/v2/enqueue"}}}}},
    }), data_path=str(tmp_path / "n"))
    try:
        _seed_errors(node)
        watch = dict(WATCH_BASE)
        watch["actions"] = {"page": {"pagerduty": {
            "description": "errors={{ctx.payload.hits.total.value}}",
            "incident_key": "errs"}}}
        call(node, "PUT", "/_watcher/watch/wp", watch)
        r = call(node, "POST", "/_watcher/watch/wp/_execute")
        assert r["watch_record"]["result"]["actions"][0]["status"] == \
            "success"
        ev = json.loads(http_fixture.requests[0]["body"])
        assert ev["routing_key"] == "sekrit"
        assert ev["payload"]["summary"] == "errors=3"
        assert ev["dedup_key"] == "errs"
    finally:
        node.close()


def test_slack_non_loopback_is_recorded_not_sent(tmp_path):
    node = Node(settings=Settings.from_dict({
        "xpack": {"notification": {"slack": {"account": {"ops": {
            "secure_url": "https://hooks.slack.com/services/T0/B0/x"}}}}},
    }), data_path=str(tmp_path / "n"))
    try:
        _seed_errors(node)
        watch = dict(WATCH_BASE)
        watch["actions"] = {"ping": {"slack": {
            "message": {"text": "hi"}}}}
        call(node, "PUT", "/_watcher/watch/ws2", watch)
        r = call(node, "POST", "/_watcher/watch/ws2/_execute")
        assert r["watch_record"]["result"]["actions"][0]["status"] == \
            "simulated"
        assert node.watcher_service.notifications[-1]["status"] == \
            "simulated"
    finally:
        node.close()


# ------------------------------------------------- monitoring HTTP exporter

def test_monitoring_http_exporter_round_trip(tmp_path):
    """Collector docs from node A land in node B's .monitoring-es
    through B's REAL REST API (template install + bulk shipping)."""
    b = Node(data_path=str(tmp_path / "b"))
    bport = b.start(0)
    a = Node(settings=Settings.from_dict({
        "xpack": {"monitoring": {"exporters": {
            "remote": {"type": "http",
                       "host": f"127.0.0.1:{bport}"},
        }}}}), data_path=str(tmp_path / "a"))
    try:
        a.indices_service.create_index("idx_a", {}, None)
        a.indices_service.get("idx_a").index_doc("1", {"x": 1})
        a.indices_service.get("idx_a").refresh()
        r = call(a, "POST", "/_monitoring/_collect")
        assert r["collected"] > 0
        # the remote template was installed on B before shipping
        t = call(b, "GET", "/_index_template/monitoring-es")
        assert t["index_templates"], t
        # and the docs are searchable on B
        call(b, "POST", "/.monitoring-es/_refresh")
        res = call(b, "POST", "/.monitoring-es/_search",
                   {"query": {"match": {"type": "node_stats"}},
                    "size": 10})
        assert res["hits"]["total"]["value"] >= 1
        # local exporter still ran on A (fan-out, not replacement)
        assert ".monitoring-es" in a.indices_service.indices
    finally:
        a.close()
        b.close()


def test_monitoring_http_exporter_sends_auth(tmp_path, http_fixture):
    host, port = http_fixture.server_address
    a = Node(settings=Settings.from_dict({
        "xpack": {"monitoring": {"exporters": {
            "remote": {"type": "http", "host": f"{host}:{port}",
                       "auth": {"username": "ship",
                                "password": "pw"}},
            "local": {"type": "local", "enabled": "false"},
        }}}}), data_path=str(tmp_path / "a"))
    try:
        call(a, "POST", "/_monitoring/_collect")
        reqs = http_fixture.requests
        assert len(reqs) >= 2           # template PUT + bulk POST
        assert reqs[0]["line"].startswith("PUT /_index_template/")
        import base64
        expect = "Basic " + base64.b64encode(b"ship:pw").decode()
        assert all(r["headers"].get("authorization") == expect
                   for r in reqs)
        # local exporter disabled: nothing indexed on A
        assert ".monitoring-es" not in a.indices_service.indices
    finally:
        a.close()


# --------------------------------------------- ML inference ingest processor

def test_inference_ingest_processor_classifies(tmp_path):
    node = Node(data_path=str(tmp_path / "n"))
    try:
        call(node, "PUT", "/_ml/trained_models/clf", {
            "model_type": "classification",
            "feature_names": ["f1", "f2"],
            "mean": [0.0, 0.0], "std": [1.0, 1.0],
            # w·x = f1 - f2 (+0 bias): positive ⇒ class "hot"
            "weights": [1.0, -1.0, 0.0],
            "classes": ["cold", "hot"],
        })
        call(node, "PUT", "/_ingest/pipeline/classify", {
            "processors": [{"inference": {
                "model_id": "clf",
                "target_field": "ml.inference",
                "field_map": {"temp": "f1", "wind": "f2"},
            }}]})
        call(node, "PUT", "/readings/_doc/1",
             {"temp": 5.0, "wind": 1.0}, pipeline="classify")
        call(node, "PUT", "/readings/_doc/2",
             {"temp": -3.0, "wind": 2.0}, pipeline="classify")
        call(node, "POST", "/readings/_refresh")
        d1 = call(node, "GET", "/readings/_doc/1")["_source"]
        d2 = call(node, "GET", "/readings/_doc/2")["_source"]
        assert d1["ml"]["inference"]["predicted_value"] == "hot"
        assert d1["ml"]["inference"]["model_id"] == "clf"
        assert d2["ml"]["inference"]["predicted_value"] == "cold"
    finally:
        node.close()


def test_inference_processor_missing_field_fails(tmp_path):
    node = Node(data_path=str(tmp_path / "n"))
    try:
        call(node, "PUT", "/_ml/trained_models/reg", {
            "model_type": "regression",
            "feature_names": ["x"], "mean": [0.0], "std": [1.0],
            "weights": [2.0, 0.0], "classes": None,
        })
        call(node, "PUT", "/_ingest/pipeline/p", {
            "processors": [{"inference": {"model_id": "reg"}}]})
        call(node, "PUT", "/d/_doc/1", {"y": 1.0}, pipeline="p",
             expect=400)
    finally:
        node.close()
