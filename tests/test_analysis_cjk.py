"""analysis-cjk-morph plugin (VERDICT r2 item 6; ref:
plugins/analysis-kuromoji/.../KuromojiAnalyzerProvider.java,
analysis-nori, analysis-smartcn): Japanese and Korean text tokenizes
into DICTIONARY FORMS through the installed plugin over _analyze, and
the analyzers drive real index/search round trips."""

import os

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.plugins import main as plugin_cli

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def node(tmp_path):
    pd = str(tmp_path / "plugins")
    plugin_cli(["install",
                os.path.join(REPO_ROOT, "plugins_src", "analysis_cjk"),
                "--plugins-dir", pd])
    n = Node(settings=Settings.from_dict({"path": {"plugins": pd}}),
             data_path=str(tmp_path / "data"))
    yield n
    n.close()


def call(node, method, path, body=None, expect=(200, 201)):
    status, r = node.rest_controller.dispatch(method, path, None, body)
    assert status in expect, r
    return r


def terms(node, analyzer, text, tokenizer=None):
    body = {"text": text}
    if tokenizer:
        body["tokenizer"] = tokenizer
    else:
        body["analyzer"] = analyzer
    r = call(node, "GET", "/_analyze", body)
    return [t["token"] for t in r["tokens"]]


def test_japanese_dictionary_forms(node):
    # compound segmentation (the kuromoji showcase input)
    assert terms(node, "kuromoji", "関西国際空港") == \
        ["関西", "国際", "空港"]
    # inflected verbs normalize to 辞書形 (dictionary form)
    assert terms(node, "kuromoji", "東京大学に行きました") == \
        ["東京", "大学", "行く"]
    assert terms(node, "kuromoji", "寿司が食べたい") == \
        ["寿司", "食べる"]
    # する-verbs split noun + する
    assert terms(node, "kuromoji", "日本語を勉強しています") == \
        ["日本語", "勉強", "する"]
    # katakana and latin pass through; particles drop
    assert terms(node, "kuromoji", "カタカナのテスト TPU") == \
        ["カタカナ", "テスト", "tpu"]


def test_korean_josa_stripping_and_verbs(node):
    assert terms(node, "nori", "학교에서 공부를 했습니다") == \
        ["학교", "공부", "하다"]
    assert terms(node, "nori", "한국어는 재미있다") == \
        ["한국어", "재미있다"]


def test_chinese_segmentation(node):
    assert terms(node, "smartcn", "我们在北京大学学习") == \
        ["我们", "在", "北京", "大学", "学习"]


def test_tokenizer_registration(node):
    assert terms(node, None, "関西国際空港",
                 tokenizer="kuromoji_tokenizer") == \
        ["関西", "国際", "空港"]


def test_japanese_search_round_trip(node):
    """Index with the kuromoji analyzer, search an INFLECTED form, match
    the dictionary form — the point of morphological analysis."""
    call(node, "PUT", "/ja", {
        "mappings": {"properties": {
            "body": {"type": "text", "analyzer": "kuromoji"}}}})
    call(node, "PUT", "/ja/_doc/1", {"body": "毎日寿司を食べる"})
    call(node, "PUT", "/ja/_doc/2", {"body": "空港まで電車で行く"})
    call(node, "POST", "/ja/_refresh")
    # query uses an inflected form (食べました) — matches the dictionary
    # form (食べる) indexed for doc 1
    r = call(node, "POST", "/ja/_search",
             {"query": {"match": {"body": "寿司を食べました"}}})
    assert [h["_id"] for h in r["hits"]["hits"]][0] == "1"
    r = call(node, "POST", "/ja/_search",
             {"query": {"match": {"body": "行きました"}}})
    assert [h["_id"] for h in r["hits"]["hits"]] == ["2"]
