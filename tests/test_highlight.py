"""Unified-highlighter analogue (ref: UnifiedHighlighter.java —
passage fragmenting, score ordering, no_match_size; HighlighterSearchIT
is the behavioral model)."""

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

PARA = (
    "The quick brown fox jumps over the lazy dog. "
    "Weather today is mild and calm with little wind. "
    "A second fox appeared near the river bank at dawn. "
    "Nothing else of note happened during the long morning hours. "
    "Later the fox and the wolf crossed the old wooden bridge together. "
    "The afternoon passed quietly in the small village square. "
    "Finally the wolf returned alone under a pale evening sky."
)


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = Node(settings=Settings.EMPTY,
             data_path=str(tmp_path_factory.mktemp("hl")))
    st, _ = n.rest_controller.dispatch(
        "PUT", "/hl", None,
        {"mappings": {"properties": {"body": {"type": "text"}}}})
    assert st == 200
    n.rest_controller.dispatch("PUT", "/hl/_doc/1", None, {"body": PARA})
    n.rest_controller.dispatch(
        "PUT", "/hl/_doc/2", None, {"body": "no matching words here"})
    n.rest_controller.dispatch("POST", "/hl/_refresh", None, None)
    yield n
    n.close()


def search(node, body):
    st, out = node.rest_controller.dispatch("POST", "/hl/_search", None,
                                            body)
    assert st == 200, out
    return out


def test_fragments_are_sized_and_scored(node):
    out = search(node, {
        "query": {"match": {"body": "fox wolf"}},
        "highlight": {"fields": {"body": {
            "fragment_size": 80, "number_of_fragments": 3}}}})
    hit = next(h for h in out["hits"]["hits"] if h["_id"] == "1")
    frags = hit["highlight"]["body"]
    assert 1 <= len(frags) <= 3
    # fragments are passages, not the whole field
    assert all(len(f) < len(PARA) for f in frags)
    assert all(len(f) <= 80 + 60 for f in frags)   # sentence-snap slack
    # score order: the best passage (both fox AND wolf) comes first
    assert "<em>fox</em>" in frags[0] and "<em>wolf</em>" in frags[0]


def test_number_of_fragments_zero_highlights_whole_field(node):
    out = search(node, {
        "query": {"match": {"body": "fox"}},
        "highlight": {"fields": {"body": {"number_of_fragments": 0}}}})
    hit = next(h for h in out["hits"]["hits"] if h["_id"] == "1")
    frags = hit["highlight"]["body"]
    assert len(frags) == 1
    assert frags[0].count("<em>fox</em>") == 3
    # the whole value is present (plus tags)
    assert frags[0].replace("<em>", "").replace("</em>", "") == PARA


def test_no_match_size(node):
    out = search(node, {
        "query": {"match_all": {}},
        "highlight": {"fields": {"body": {"no_match_size": 60}}}})
    hit = next(h for h in out["hits"]["hits"] if h["_id"] == "2")
    frags = hit["highlight"]["body"]
    assert len(frags) == 1 and "<em>" not in frags[0]
    assert 0 < len(frags[0]) <= 120
    # doc without no_match text still excluded when no terms match
    out2 = search(node, {
        "query": {"match": {"body": "fox"}},
        "highlight": {"fields": {"body": {}}}})
    h2 = next(h for h in out2["hits"]["hits"] if h["_id"] == "1")
    assert "body" in h2["highlight"]


def test_custom_tags_and_source_order(node):
    out = search(node, {
        "query": {"match": {"body": "wolf"}},
        "highlight": {"pre_tags": ["[["], "post_tags": ["]]"],
                      "fields": {"body": {
                          "fragment_size": 60,
                          "number_of_fragments": 5,
                          "order": "none"}}}})
    hit = next(h for h in out["hits"]["hits"] if h["_id"] == "1")
    frags = hit["highlight"]["body"]
    assert any("[[wolf]]" in f for f in frags)
    # order=none: fragments appear in source order
    pos = [PARA.find(f.replace("[[", "").replace("]]", "")[:25])
           for f in frags]
    assert pos == sorted(pos)


def test_plain_type_keeps_whole_field(node):
    out = search(node, {
        "query": {"match": {"body": "fox"}},
        "highlight": {"fields": {"body": {"type": "plain"}}}})
    hit = next(h for h in out["hits"]["hits"] if h["_id"] == "1")
    frags = hit["highlight"]["body"]
    assert len(frags) == 1
    assert frags[0].replace("<em>", "").replace("</em>", "") == PARA


# ---------------------------------------------------------------------------
# FVH analogue (ref: FastVectorHighlighter.java — matched_fields,
# match-centered fragments, boundary scanning)
# ---------------------------------------------------------------------------

def _dispatch(node, method, path, body):
    st, out = node.rest_controller.dispatch(method, path, None, body)
    assert st in (200, 201), out
    return out


def test_fvh_matched_fields_merges_subfield_hits(node):
    _dispatch(node, "PUT", "/books2", {"mappings": {"properties": {
        "title": {"type": "text",
                  "fields": {"exact": {"type": "text",
                                       "analyzer": "whitespace"}}}}}})
    _dispatch(node, "PUT", "/books2/_doc/1",
              {"title": "Running with Scissors"})
    _dispatch(node, "POST", "/books2/_refresh", None)
    r = _dispatch(node, "POST", "/books2/_search", {
        "query": {"match": {"title.exact": "Running"}},
        "highlight": {"fields": {"title": {
            "type": "fvh",
            "matched_fields": ["title", "title.exact"]}}}})
    hit = r["hits"]["hits"][0]
    assert hit["highlight"]["title"][0].count("<em>") == 1
    assert "<em>Running</em>" in hit["highlight"]["title"][0]


def test_fvh_fragments_center_on_matches(node):
    filler = "lorem ipsum dolor sit amet " * 20
    text = filler + "the zebra appears here " + filler
    _dispatch(node, "PUT", "/books3/_doc/1", {"body": text})
    _dispatch(node, "POST", "/books3/_refresh", None)
    r = _dispatch(node, "POST", "/books3/_search", {
        "query": {"match": {"body": "zebra"}},
        "highlight": {"fields": {"body": {
            "type": "fvh", "fragment_size": 60,
            "number_of_fragments": 2}}}})
    frags = r["hits"]["hits"][0]["highlight"]["body"]
    assert len(frags) >= 1
    assert "<em>zebra</em>" in frags[0]
    # the fragment is a WINDOW around the match, not the whole field
    assert len(frags[0]) < 140
