"""EQL plugin tests (model: x-pack/plugin/eql execution tests — event
queries, sequences with maxspan/until, pipes)."""

import pytest

from elasticsearch_tpu.node import Node

MAPPINGS = {
    "properties": {
        "etype": {"type": "keyword"},
        "ts": {"type": "date"},
        "user": {"type": "keyword"},
        "proc": {"type": "keyword"},
        "pid": {"type": "long"},
        "port": {"type": "long"},
    }
}

# a process/network event log: two users, one full attack chain for bob
EVENTS = [
    {"etype": "process", "ts": 1000, "user": "bob", "proc": "cmd.exe", "pid": 1},
    {"etype": "process", "ts": 2000, "user": "amy", "proc": "calc.exe", "pid": 2},
    {"etype": "network", "ts": 3000, "user": "bob", "proc": "cmd.exe",
     "pid": 1, "port": 443},
    {"etype": "process", "ts": 4000, "user": "amy", "proc": "word.exe", "pid": 4},
    {"etype": "file", "ts": 5000, "user": "bob", "proc": "cmd.exe", "pid": 1},
    {"etype": "process", "ts": 90_000_000, "user": "amy", "proc": "cmd.exe",
     "pid": 9},
    {"etype": "network", "ts": 190_000_000, "user": "amy", "proc": "cmd.exe",
     "pid": 9, "port": 80},
]


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("eql")
    n = Node(data_path=str(tmp / "data"))
    idx = n.indices_service.create_index(
        "logs", {"index.number_of_shards": 2}, MAPPINGS)
    for i, d in enumerate(EVENTS):
        idx.index_doc(str(i), d)
    idx.refresh()
    yield n
    n.close()


def eql(node, query, **body):
    status, r = node.rest_controller.dispatch(
        "POST", "/logs/_eql/search", {},
        {"query": query, "timestamp_field": "ts",
         "event_category_field": "etype", **body})
    assert status == 200, r
    return r


def test_event_query(node):
    r = eql(node, 'process where proc == "cmd.exe"')
    events = r["hits"]["events"]
    assert [e["_source"]["user"] for e in events] == ["bob", "amy"]
    assert r["hits"]["total"]["value"] == 2


def test_any_category(node):
    r = eql(node, 'any where user == "amy"', size=10)
    assert r["hits"]["total"]["value"] == 4


def test_event_query_functions(node):
    r = eql(node, 'process where wildcard(proc, "c*.exe")', size=10)
    procs = [e["_source"]["proc"] for e in r["hits"]["events"]]
    assert sorted(set(procs)) == ["calc.exe", "cmd.exe"]
    r = eql(node, 'process where startsWith(proc, "w")')
    assert [e["_source"]["proc"] for e in r["hits"]["events"]] == ["word.exe"]


def test_numeric_condition(node):
    r = eql(node, "network where port > 100")
    assert [e["_source"]["port"] for e in r["hits"]["events"]] == [443]


def test_sequence_by_key(node):
    r = eql(node, 'sequence by user [process where true] '
                  '[network where true]')
    seqs = r["hits"]["sequences"]
    assert len(seqs) == 2
    by_user = {s["join_keys"][0]: s for s in seqs}
    assert by_user["bob"]["events"][0]["_source"]["ts"] == 1000
    assert by_user["bob"]["events"][1]["_source"]["ts"] == 3000
    assert by_user["amy"]["events"][0]["_source"]["ts"] == 90_000_000


def test_sequence_maxspan(node):
    # amy's process→network pair is 100000s apart; maxspan kills it
    r = eql(node, 'sequence by user with maxspan=10s '
                  '[process where true] [network where true]')
    seqs = r["hits"]["sequences"]
    assert len(seqs) == 1
    assert seqs[0]["join_keys"] == ["bob"]


def test_sequence_three_stages(node):
    r = eql(node, 'sequence by user [process where true] '
                  '[network where true] [file where true]')
    seqs = r["hits"]["sequences"]
    assert len(seqs) == 1
    assert [e["_source"]["etype"] for e in seqs[0]["events"]] == [
        "process", "network", "file"]


def test_sequence_until(node):
    # a process event for amy between her stages kills the partial via
    # until — use bob's file event at ts 5000 as the canary instead
    r = eql(node, 'sequence by user [process where true] '
                  '[file where true] until [network where true]')
    # bob: process@1000 then network@3000 kills it before file@5000
    assert r["hits"]["sequences"] == []


def test_head_pipe(node):
    r = eql(node, "any where true | head 3", size=10)
    assert r["hits"]["total"]["value"] == 3
    assert [e["_source"]["ts"] for e in r["hits"]["events"]] == [
        1000, 2000, 3000]


def test_tail_pipe(node):
    r = eql(node, "any where true | tail 2", size=10)
    assert [e["_source"]["ts"] for e in r["hits"]["events"]] == [
        90_000_000, 190_000_000]


def test_filter_body(node):
    r = eql(node, "any where true", size=10,
            filter={"term": {"user": {"value": "bob"}}})
    assert r["hits"]["total"]["value"] == 3


def test_in_and_not(node):
    r = eql(node, 'process where proc in ("cmd.exe", "word.exe") and '
                  'not user == "bob"', size=10)
    assert [e["_source"]["proc"] for e in r["hits"]["events"]] == [
        "word.exe", "cmd.exe"]


def test_event_missing_timestamp_skipped(node):
    # a doc without the timestamp field must not 500 the search
    idx = node.indices_service.get("logs")
    idx.index_doc("no-ts", {"etype": "process", "user": "zed",
                            "proc": "rogue.exe"})
    idx.refresh()
    r = eql(node, "process where true", size=20)
    users = [e["_source"]["user"] for e in r["hits"]["events"]]
    assert "zed" not in users
