"""Cluster-durable cursors under the deterministic harness: multi-node
scroll paging (byte-equal to a single search), seeded node kills
mid-scroll (failover to another copy at the same continuation point
when the cursor is portable, typed `search_context_missing_exception`
when it is not — never a hang, never silent truncation), PIT reads
surviving an explicit `_cluster/reroute` relocation via retention-lease
transfer, async search cancelled through its `GET /_tasks`-visible
parent task from a NON-owning node, and a same-seed byte-identical
replay of the whole scripted scenario.

Single-node companions pin the resumable-drain contract
(`resumable_scroll_batches`) that `_bulk_by_scroll` and the EQL
windowed fetch ride."""

import json

import pytest

from elasticsearch_tpu.common.errors import SearchContextMissingException
from elasticsearch_tpu.node import Node
from test_cluster_node import SimDataCluster, _index_some_docs

SORTED_BODY = {"query": {"match_all": {}}, "sort": [{"n": "desc"}]}


# ---------------------------------------------------------------------------
# harness helpers
# ---------------------------------------------------------------------------


def _setup(cluster, shards=3, replicas=1, n=24, index="logs"):
    master = cluster.stabilise()
    cluster.call(master.create_index, index, number_of_shards=shards,
                 number_of_replicas=replicas)
    cluster.run_for(60)
    _index_some_docs(cluster, master, index=index, n=n)
    return master


def _hit_ids(resp):
    return [h["_id"] for h in resp["hits"]["hits"]]


def _drain_scroll(cluster, coord, index, body, size, between_pages=None):
    """Open a scroll and page it to exhaustion; returns (ids, pages)."""
    b = dict(body)
    b["size"] = size
    resp = cluster.call(coord.search, index, b, scroll=60.0)
    ids, pages = _hit_ids(resp), [resp]
    sid = resp["_scroll_id"]
    while resp["hits"]["hits"]:
        if between_pages is not None:
            between_pages(len(pages), sid)
            between_pages = None      # fire the chaos exactly once
        resp = cluster.call(coord.scroll, sid, 60.0)
        sid = resp["_scroll_id"]
        ids.extend(_hit_ids(resp))
        pages.append(resp)
    cluster.call(coord.clear_scroll, [sid])
    return ids, pages


def _reader_context_nodes(cluster):
    return {nid: sorted(cn.data_node.reader_contexts)
            for nid, cn in sorted(cluster.cluster_nodes.items())
            if cn.data_node.reader_contexts}


def _assert_no_cursor_state(cluster):
    """Leak guard: no reader contexts, scroll/pit records, or pit
    retention leases anywhere in the fleet. (Frees are fire-and-forget
    RPCs — drive the sim so they deliver before asserting.)"""
    cluster.run_for(5)
    for nid, cn in sorted(cluster.cluster_nodes.items()):
        assert not cn.data_node.reader_contexts, \
            f"{nid}: leaked reader contexts {cn.data_node.reader_contexts}"
        assert cn.search_service.open_scroll_count() == 0, nid
        assert cn.search_service.open_pit_count() == 0, nid
        for key, shard in sorted(cn.data_node.shards.items()):
            if shard.tracker is None:
                continue
            pit_leases = [lid for lid in shard.tracker.get_retention_leases()
                          if lid.startswith("pit/")]
            assert not pit_leases, f"{nid}{key}: leaked leases {pit_leases}"


# ---------------------------------------------------------------------------
# scroll: multi-node paging equals one single-shot search
# ---------------------------------------------------------------------------


def test_multinode_scroll_equals_single_search(tmp_path):
    """3 nodes / 3 shards / 1 replica: paging a sorted scroll to
    exhaustion yields EXACTLY the ids of one big search — same order,
    no duplicates, no gaps — and every page re-stamps the pinned
    total instead of re-counting a moving index."""
    cluster = SimDataCluster(3, tmp_path, seed=11)
    master = _setup(cluster, n=24)

    whole = cluster.call(master.search, "logs",
                         {**SORTED_BODY, "size": 100})
    assert whole["hits"]["total"]["value"] == 24

    ids, pages = _drain_scroll(cluster, master, "logs", SORTED_BODY, 7)
    assert ids == _hit_ids(whole), "scroll pages drifted from the search"
    assert len(ids) == len(set(ids)) == 24
    assert [len(p["hits"]["hits"]) for p in pages] == [7, 7, 7, 3, 0]
    for p in pages:
        assert p["hits"]["total"] == {"value": 24, "relation": "eq"}
    _assert_no_cursor_state(cluster)


def test_clear_scroll_frees_contexts_on_every_node(tmp_path):
    cluster = SimDataCluster(3, tmp_path, seed=13)
    master = _setup(cluster, n=12)
    resp = cluster.call(master.search, "logs",
                        {**SORTED_BODY, "size": 4}, scroll=60.0)
    assert _reader_context_nodes(cluster), "scroll pinned no contexts"
    out = cluster.call(master.clear_scroll, [resp["_scroll_id"]])
    assert out == {"succeeded": True, "num_freed": 1}
    cluster.run_for(5)      # remote free RPCs drain
    _assert_no_cursor_state(cluster)


def test_scroll_keepalive_expiry_is_typed(tmp_path):
    """An expired scroll fails typed on the SCHEDULER clock — lazy
    reaping, no background timer to perturb seeded interleavings."""
    cluster = SimDataCluster(3, tmp_path, seed=19)
    master = _setup(cluster, n=12)
    resp = cluster.call(master.search, "logs",
                        {**SORTED_BODY, "size": 4}, scroll=5.0)
    cluster.run_for(30)     # sail past the keep-alive
    with pytest.raises(SearchContextMissingException):
        cluster.call(master.scroll, resp["_scroll_id"], 5.0)
    cluster.run_for(5)
    _assert_no_cursor_state(cluster)


# ---------------------------------------------------------------------------
# chaos: node killed mid-scroll
# ---------------------------------------------------------------------------


def _context_victim(cluster, coord, scroll_id, require_cursor=False):
    """A non-coordinator node that owns a live reader context of this
    scroll (optionally one whose shard has already emitted hits)."""
    rec = coord.search_service._scrolls[scroll_id]
    for _key, e in sorted(rec["shards"].items()):
        if e["node"] == coord.local_node.node_id:
            continue
        if require_cursor and e["cursor"] is None:
            continue
        return e["node"]
    return None


@pytest.mark.chaos(seed=43)
def test_node_kill_mid_scroll_fails_over_exactly(tmp_path, chaos_seed):
    """Replicated index + explicit sort: the cursor is PORTABLE, so a
    node killed between pages fails over to another copy at the same
    continuation point — the drained stream is still byte-equal to the
    healthy single search, with every doc delivered exactly once."""
    cluster = SimDataCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, shards=3, replicas=1, n=24)
    whole_ids = _hit_ids(cluster.call(
        master.search, "logs", {**SORTED_BODY, "size": 100}))

    killed = {}

    def kill_context_owner(_page_no, sid):
        victim = _context_victim(cluster, master, sid)
        assert victim is not None, \
            f"seed={chaos_seed}: every context landed on the coordinator"
        killed["node"] = victim
        cluster.stop_node(victim)
        cluster.run_for(30)     # node-left, replicas promoted

    ids, _pages = _drain_scroll(cluster, master, "logs", SORTED_BODY, 7,
                                between_pages=kill_context_owner)
    assert killed, "chaos never fired"
    assert ids == whole_ids, (
        f"seed={chaos_seed}: scroll after killing {killed['node']} "
        f"drifted: {ids} != {whole_ids}")
    assert master.search_service.cursor_failovers >= 1, \
        f"seed={chaos_seed}: failover path never taken"
    cluster.run_for(5)
    for nid, cn in cluster.cluster_nodes.items():
        assert not cn.data_node.reader_contexts, f"seed={chaos_seed}: {nid}"


@pytest.mark.chaos(seed=47)
def test_node_kill_without_sort_fails_typed_not_silent(tmp_path,
                                                       chaos_seed):
    """No explicit sort → score order → the continuation point is NOT
    portable to another copy once hits were emitted. Killing the
    context owner must surface the typed
    `search_context_missing_exception` — never a hang, and never a
    silently truncated or duplicated stream."""
    cluster = SimDataCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, shards=3, replicas=1, n=24)
    body = {"query": {"match": {"body": "fox"}}}

    resp = cluster.call(master.search, "logs", {**body, "size": 10},
                        scroll=60.0)
    sid = resp["_scroll_id"]
    victim = _context_victim(cluster, master, sid, require_cursor=True)
    while victim is None:      # page until a non-coordinator shard emits
        resp = cluster.call(master.scroll, sid, 60.0)
        assert resp["hits"]["hits"], \
            f"seed={chaos_seed}: exhausted before chaos could fire"
        victim = _context_victim(cluster, master, sid,
                                 require_cursor=True)
    cluster.stop_node(victim)
    cluster.run_for(30)

    with pytest.raises(SearchContextMissingException):
        cluster.call(master.scroll, sid, 60.0)
    # the failed scroll frees its record; a retry is typed too, not 500
    with pytest.raises(SearchContextMissingException):
        cluster.call(master.scroll, sid, 60.0)
    assert master.search_service.open_scroll_count() == 0
    cluster.run_for(5)
    for nid, cn in cluster.cluster_nodes.items():
        assert not cn.data_node.reader_contexts, f"seed={chaos_seed}: {nid}"


# ---------------------------------------------------------------------------
# PIT: lease-backed, survives relocation
# ---------------------------------------------------------------------------


def test_pit_survives_shard_relocation(tmp_path):
    """A PIT pins its reader context under a `pit/…` retention lease on
    the primary. An explicit `_cluster/reroute` move transfers the
    lease and re-opens the context at the SAME pinned segment view on
    the new primary — reads before and after the move are identical,
    and writes made after the PIT opened stay invisible throughout."""
    cluster = SimDataCluster(3, tmp_path, seed=23)
    master = _setup(cluster, shards=1, replicas=0, n=20)

    pit = cluster.call(master.open_pit, "logs", 600.0)["id"]
    pit_body = {**SORTED_BODY, "size": 50, "pit": {"id": pit}}
    before = cluster.call(master.search, "_all", pit_body)
    assert before["hits"]["total"]["value"] == 20

    # writes after the PIT opened: visible to a plain search only
    late = [{"op": "index", "id": f"late-{i}",
             "source": {"body": f"late fox {i}", "n": 100 + i}}
            for i in range(5)]
    assert cluster.call(master.bulk, "logs", late)["errors"] == []
    cluster.call(master.refresh)
    assert cluster.call(
        master.search, "logs",
        {**SORTED_BODY, "size": 50})["hits"]["total"]["value"] == 20 + 5
    assert cluster.call(master.search, "_all", pit_body)[
        "hits"]["total"]["value"] == 20

    state = master.state
    src = state.routing_table.index("logs").shard(0).primary.current_node_id
    tgt = next(n.node_id for n in cluster.nodes if n.node_id != src)
    src_leases = [
        lid for lid in cluster.cluster_nodes[src].data_node
        .shards[("logs", 0)].tracker.get_retention_leases()
        if lid.startswith("pit/")]
    assert src_leases, "PIT opened without a retention lease"

    cluster.call(master.reroute, commands=[{"move": {
        "index": "logs", "shard": 0,
        "from_node": src, "to_node": tgt}}])
    cluster.run_for(60)
    assert master.state.routing_table.index("logs").shard(0) \
        .primary.current_node_id == tgt

    transfers = sum(cn.data_node.lease_transfers
                    for cn in cluster.cluster_nodes.values())
    assert transfers >= 1, "relocation never transferred the PIT lease"
    tgt_dn = cluster.cluster_nodes[tgt].data_node
    assert any(ctx.pit for ctx in tgt_dn.reader_contexts.values()), \
        "pinned context did not travel with the handoff"
    assert src_leases == [
        lid for lid in tgt_dn.shards[("logs", 0)]
        .tracker.get_retention_leases() if lid.startswith("pit/")]

    after = cluster.call(master.search, "_all", pit_body)
    assert _hit_ids(after) == _hit_ids(before), \
        "PIT view changed across relocation"
    assert after["hits"]["total"]["value"] == 20

    assert cluster.call(master.close_pit, pit) == \
        {"succeeded": True, "num_freed": 1}
    cluster.run_for(5)
    _assert_no_cursor_state(cluster)
    with pytest.raises(SearchContextMissingException):
        cluster.call(master.search, "_all", pit_body)


# ---------------------------------------------------------------------------
# async search: cancel through `_tasks` from a non-owning node
# ---------------------------------------------------------------------------


def _call_fast(cluster, fn, *args, timeout=30.0, **kwargs):
    """cluster.call with 0.05s sim steps so probes resolve while a
    slowed search is still mid-flight."""
    box = {}

    def on_done(result, err=None):
        box["result"], box["err"] = result, err

    fn(*args, **kwargs, on_done=on_done)
    waited = 0.0
    while "result" not in box and "err" not in box and waited < timeout:
        cluster.run_for(0.05)
        waited += 0.05
    assert "result" in box or "err" in box, "call never completed"
    if box.get("err") is not None:
        raise box["err"]
    return box["result"]


@pytest.mark.chaos(seed=53)
def test_async_search_cancelled_from_non_owning_node(tmp_path,
                                                     chaos_seed):
    """Submit on the owner, then list/cancel/get/delete from a DIFFERENT
    node: the id routes every op to the owner, the running fan-out is a
    `GET /_tasks`-visible cancellable parent, and after the cancel +
    delete the fleet holds zero tasks, contexts, or async records."""
    from elasticsearch_tpu.search.async_search import ASYNC_SUBMIT_ACTION

    cluster = SimDataCluster(3, tmp_path, seed=chaos_seed)
    master = _setup(cluster, shards=4, replicas=0, n=24)
    for cn in cluster.cluster_nodes.values():
        cn.search_service.query_step_delay = 1.0

    sub = _call_fast(cluster, master.submit_async_search, "logs",
                     {**SORTED_BODY, "size": 5},
                     {"wait_for_completion_timeout": "0s",
                      "keep_alive": "1m"})
    assert sub["is_running"] and sub["is_partial"], \
        f"seed={chaos_seed}: search finished before the wait elapsed"
    owner_task = sub["task"]
    assert owner_task.startswith(master.local_node.node_id + ":")

    other = next(cn for nid, cn in sorted(cluster.cluster_nodes.items())
                 if nid != master.local_node.node_id)
    listed = _call_fast(cluster, other.list_tasks,
                        {"group_by": "none", "detailed": True})
    assert owner_task in listed["tasks"], \
        f"seed={chaos_seed}: submit task invisible in _tasks: {listed}"
    assert listed["tasks"][owner_task]["action"] == ASYNC_SUBMIT_ACTION
    assert listed["tasks"][owner_task]["cancellable"] is True

    cancel = _call_fast(cluster, other.cancel_task, owner_task)
    assert cancel.get("node_failures", []) == []
    cluster.run_for(10)     # fan-out dies, bans swept one beat later

    got = _call_fast(cluster, other.get_async_search, sub["id"], {})
    assert got["is_running"] is False, f"seed={chaos_seed}: {got}"
    assert got["is_partial"] is True
    # the cancel surfaces TYPED: either a top-level error or per-shard
    # task_cancelled_exception failures folded into the partial result
    assert "task_cancelled" in json.dumps(got), \
        f"seed={chaos_seed}: cancel did not surface typed: {got}"

    assert _call_fast(cluster, other.delete_async_search, sub["id"]) == \
        {"acknowledged": True}
    # across the transport the typed miss arrives wrapped — match on
    # the carried type, not the wrapper class
    with pytest.raises(Exception, match="ResourceNotFound"):
        _call_fast(cluster, other.get_async_search, sub["id"], {})
    cluster.run_for(5)
    assert master.async_search.open_async_search_count() == 0
    _assert_no_cursor_state(cluster)


# ---------------------------------------------------------------------------
# determinism: same seed, byte-identical cursor transcript
# ---------------------------------------------------------------------------


def _cursor_transcript(tmp_path, seed):
    """A scripted scroll+PIT+async scenario; returns its canonical JSON
    transcript."""
    cluster = SimDataCluster(3, tmp_path, seed=seed)
    master = _setup(cluster, n=18)
    out = []
    ids, pages = _drain_scroll(cluster, master, "logs", SORTED_BODY, 5)
    out.append(ids)
    out.extend(pages)
    pit = cluster.call(master.open_pit, "logs", 300.0)
    out.append(pit)
    out.append(cluster.call(master.search, "_all",
                            {**SORTED_BODY, "size": 9,
                             "pit": {"id": pit["id"]}}))
    out.append(cluster.call(master.close_pit, pit["id"]))
    out.append(cluster.call(master.submit_async_search, "logs",
                            {**SORTED_BODY, "size": 3},
                            {"wait_for_completion_timeout": "30s"}))
    out.append(cluster.call(master.delete_async_search, out[-1]["id"]))
    cluster.run_for(5)
    return json.dumps(out, sort_keys=True)


def test_same_seed_cursor_replay_is_byte_identical(tmp_path):
    a = _cursor_transcript(tmp_path / "a", seed=67)
    b = _cursor_transcript(tmp_path / "b", seed=67)
    assert a == b, "same-seed cursor run diverged"


# ---------------------------------------------------------------------------
# single-node: the resumable drain the reindex worker and EQL ride
# ---------------------------------------------------------------------------


@pytest.fixture()
def single_node(tmp_path):
    n = Node(data_path=str(tmp_path / "data"))
    idx = n.indices_service.create_index(
        "logs", {"index.number_of_shards": 2},
        {"properties": {"n": {"type": "integer"},
                        "body": {"type": "text"}}})
    for i in range(17):
        idx.index_doc(f"doc-{i}", {"n": i, "body": f"fox {i}"})
    idx.refresh()
    yield n
    n.close()


def test_resumable_drain_survives_lost_context_with_sort(single_node):
    """`resumable_scroll_batches` with an explicit sort: the scroll
    record is destroyed behind the drain's back after the first batch;
    the drain re-opens with `search_after` at the last emitted sort
    key and the total stream is still exact — no gap, no repeat."""
    from elasticsearch_tpu.search.service import resumable_scroll_batches

    svc = single_node.search_service
    body = {"query": {"match_all": {}}, "sort": [{"n": "asc"}]}
    resumes = []
    gen = resumable_scroll_batches(svc, "logs", body, 5,
                                   on_resume=lambda: resumes.append(1))
    got = [h["_id"] for h in next(gen)]
    svc.clear_scroll(["_all"])          # the "node kill"
    for batch in gen:
        got.extend(h["_id"] for h in batch)
    assert got == [f"doc-{i}" for i in range(17)]
    assert len(resumes) == 1, "resume path never exercised"


def test_resumable_drain_survives_lost_context_without_sort(single_node):
    """Without a sort the resume re-opens the stream and skips the
    already-emitted prefix by count — same exact id sequence."""
    from elasticsearch_tpu.search.service import resumable_scroll_batches

    svc = single_node.search_service
    body = {"query": {"match_all": {}}}
    baseline = [h["_id"] for batch in resumable_scroll_batches(
        svc, "logs", dict(body), 4) for h in batch]
    assert len(baseline) == 17

    resumes = []
    gen = resumable_scroll_batches(svc, "logs", dict(body), 4,
                                   on_resume=lambda: resumes.append(1))
    got = [h["_id"] for h in next(gen)]
    got.extend(h["_id"] for h in next(gen))
    svc.clear_scroll(["_all"])
    for batch in gen:
        got.extend(h["_id"] for h in batch)
    assert got == baseline
    assert len(resumes) == 1


def test_eql_windowed_fetch_matches_unwindowed(single_node, monkeypatch):
    """Satellite guard: shrinking EQL_FETCH_WINDOW far below the result
    set changes memory behaviour only — the response is identical."""
    import elasticsearch_tpu.xpack.eql as eql_mod

    def run():
        status, r = single_node.rest_controller.dispatch(
            "POST", "/logs/_eql/search", {},
            {"query": "any where true", "timestamp_field": "n",
             "event_category_field": "body", "size": 17})
        assert status == 200, r
        r.pop("took", None)     # wall-clock latency, not a result
        return r

    monkeypatch.setattr(eql_mod, "EQL_FETCH_WINDOW", 3)
    windowed = run()
    monkeypatch.setattr(eql_mod, "EQL_FETCH_WINDOW", 1000)
    whole = run()
    assert windowed == whole
    assert len(whole["hits"]["events"]) == 17
