"""Mapping/document-parser tests (model: the reference's DocumentParserTests,
DynamicMappingTests, MapperServiceTests)."""

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
    StrictDynamicMappingException,
)
from elasticsearch_tpu.index.mapper import MapperService


MAPPINGS = {
    "properties": {
        "title": {"type": "text"},
        "tags": {"type": "keyword"},
        "views": {"type": "long"},
        "score": {"type": "float"},
        "published": {"type": "boolean"},
        "created": {"type": "date"},
        "embedding": {"type": "dense_vector", "dims": 4},
        "author": {"properties": {"name": {"type": "text"}}},
    }
}


def make_service():
    return MapperService(mappings=MAPPINGS)


def test_parse_typed_fields():
    svc = make_service()
    doc = svc.parse("1", {
        "title": "The quick brown fox",
        "tags": ["a", "b"],
        "views": 42,
        "score": "1.5",
        "published": True,
        "created": "2020-06-15",
        "embedding": [1.0, 0.0, 0.0, 0.0],
        "author": {"name": "Jane Doe"},
    })
    assert [t.term for t in doc.text_tokens["title"]] == ["the", "quick", "brown", "fox"]
    assert doc.keyword_terms["tags"] == ["a", "b"]
    assert doc.numeric_values["views"] == [42.0]
    assert doc.numeric_values["score"] == [1.5]
    assert doc.numeric_values["published"] == [1.0]
    assert doc.numeric_values["created"][0] == 1592179200000.0
    assert np.allclose(doc.vectors["embedding"], [1, 0, 0, 0])
    assert [t.term for t in doc.text_tokens["author.name"]] == ["jane", "doe"]
    assert doc.field_length("title") == 4


def test_dynamic_mapping_infers_types():
    svc = MapperService()
    doc = svc.parse("1", {"name": "hello world", "count": 7, "ratio": 0.5, "flag": False})
    assert svc.field_type("name").type_name == "text"
    assert svc.field_type("name.keyword").type_name == "keyword"
    assert svc.field_type("count").type_name == "long"
    assert svc.field_type("ratio").type_name == "float"
    assert svc.field_type("flag").type_name == "boolean"
    assert "name" in doc.dynamic_mappings
    # dynamic string got indexed both as text and keyword
    assert [t.term for t in doc.text_tokens["name"]] == ["hello", "world"]
    assert doc.keyword_terms["name.keyword"] == ["hello world"]


def test_dynamic_date_detection():
    svc = MapperService()
    svc.parse("1", {"ts": "2021-03-04T05:06:07"})
    assert svc.field_type("ts").type_name == "date"


def test_strict_dynamic_rejects():
    svc = MapperService(mappings={"dynamic": "strict", "properties": {"a": {"type": "long"}}})
    with pytest.raises(StrictDynamicMappingException):
        svc.parse("1", {"a": 1, "unknown": "x"})


def test_dynamic_false_ignores():
    svc = MapperService(mappings={"dynamic": "false", "properties": {"a": {"type": "long"}}})
    doc = svc.parse("1", {"a": 1, "unknown": "x"})
    assert svc.field_type("unknown") is None
    assert "unknown" not in doc.text_tokens


def test_numeric_range_validation():
    svc = MapperService(mappings={"properties": {"b": {"type": "byte"}}})
    with pytest.raises(MapperParsingException):
        svc.parse("1", {"b": 1000})


def test_bad_number_raises():
    svc = MapperService(mappings={"properties": {"n": {"type": "integer"}}})
    with pytest.raises(MapperParsingException):
        svc.parse("1", {"n": "not-a-number"})


def test_dense_vector_dim_check():
    svc = MapperService(mappings={"properties": {"v": {"type": "dense_vector", "dims": 3}}})
    with pytest.raises(MapperParsingException):
        svc.parse("1", {"v": [1.0, 2.0]})
    with pytest.raises(MapperParsingException):
        MapperService(mappings={"properties": {"v": {"type": "dense_vector", "dims": 4096}}})


def test_merge_conflicting_type_rejected():
    svc = make_service()
    with pytest.raises(IllegalArgumentException):
        svc.merge({"properties": {"views": {"type": "text"}}})


def test_merge_adds_fields():
    svc = make_service()
    svc.merge({"properties": {"extra": {"type": "keyword"}}})
    assert svc.field_type("extra").type_name == "keyword"


def test_mapping_roundtrip():
    svc = make_service()
    out = svc.to_mapping()
    assert out["properties"]["title"]["type"] == "text"
    assert out["properties"]["author"]["properties"]["name"]["type"] == "text"
    assert out["properties"]["embedding"] == {"type": "dense_vector", "dims": 4}


def test_multivalue_text_position_gap():
    svc = MapperService(mappings={"properties": {"t": {"type": "text"}}})
    doc = svc.parse("1", {"t": ["foo bar", "baz"]})
    toks = doc.text_tokens["t"]
    assert [t.term for t in toks] == ["foo", "bar", "baz"]
    assert toks[2].position >= toks[1].position + 100  # gap between values
