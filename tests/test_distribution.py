"""Distribution packaging (ref: distribution/ — archives, packages,
docker): the tar layout boots as an external process through its own
bin/elasticsearch script reading config/elasticsearch.yml, the plugin
CLI wrapper works against the unpacked layout, and the deb/rpm/docker
stagings carry the systemd unit + control metadata."""

import json
import os
import signal
import subprocess
import sys
import tarfile
import time
import urllib.request

import pytest

from elasticsearch_tpu import distribution


def test_tar_layout_and_contents(tmp_path):
    tar_path = distribution.build_tar(str(tmp_path))
    assert tar_path.endswith("-linux.tar.gz")
    with tarfile.open(tar_path) as tf:
        names = tf.getnames()
    root = f"elasticsearch-tpu-{distribution.VERSION}"
    for required in (
            f"{root}/bin/elasticsearch",
            f"{root}/bin/elasticsearch-plugin",
            f"{root}/bin/elasticsearch-keystore",
            f"{root}/bin/elasticsearch-sql-cli",
            f"{root}/config/elasticsearch.yml",
            f"{root}/lib/elasticsearch_tpu/__main__.py",
            f"{root}/lib/elasticsearch_tpu/node.py",
            f"{root}/plugins_src/analysis_phonetic/plugin.json"):
        assert required in names, required
    # bytecode caches do not ship
    assert not any("__pycache__" in n for n in names)


def test_tar_boots_and_serves(tmp_path):
    """The unpacked archive is a self-sufficient install: its OWN
    bin/elasticsearch (not the repo checkout) starts a node configured
    by its OWN config/elasticsearch.yml."""
    tar_path = distribution.build_tar(str(tmp_path))
    with tarfile.open(tar_path) as tf:
        tf.extractall(str(tmp_path / "x"), filter="data")
    root = str(tmp_path / "x" / f"elasticsearch-tpu-{distribution.VERSION}")
    # config file feeds settings (cluster.name proves the yml is read)
    with open(os.path.join(root, "config", "elasticsearch.yml"),
              "a") as fh:
        fh.write("\ncluster.name: from-config-file\nhttp.port: 0\n"
                 "http.native: false\n"
                 f"path.data: {tmp_path / 'yml-data'}\n")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [os.path.join(root, "bin", "elasticsearch"), "--quiet"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path))
    try:
        import select
        deadline = time.time() + 420
        line = ""
        while time.time() < deadline:
            r, _, _ = select.select([proc.stdout], [], [], 5.0)
            if r:
                line = proc.stdout.readline()
                break
            if proc.poll() is not None:
                break
        assert line.startswith("started node="), (
            line, proc.poll(),
            proc.stderr.read() if proc.poll() is not None else "")
        port = int(line.rsplit("port=", 1)[1])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=30) as resp:
            root_doc = json.loads(resp.read())
        assert root_doc["cluster_name"] == "from-config-file"
        # path.data from the yml is honored (ES_DATA was not set)
        assert os.path.isdir(str(tmp_path / "yml-data"))
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_plugin_cli_wrapper(tmp_path):
    root = distribution.stage(str(tmp_path))
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    r = subprocess.run(
        [os.path.join(root, "bin", "elasticsearch-plugin"), "install",
         os.path.join(root, "plugins_src", "analysis_phonetic"),
         "--plugins-dir", str(tmp_path / "pd")],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    r = subprocess.run(
        [os.path.join(root, "bin", "elasticsearch-plugin"), "list",
         "--plugins-dir", str(tmp_path / "pd")],
        capture_output=True, text=True, timeout=120, env=env)
    assert "analysis-phonetic" in r.stdout


def test_deb_staging(tmp_path):
    pkg = distribution.write_deb(str(tmp_path))
    control = open(os.path.join(pkg, "DEBIAN", "control")).read()
    assert "Package: elasticsearch-tpu" in control
    assert f"Version: {distribution.VERSION}" in control
    postinst = os.path.join(pkg, "DEBIAN", "postinst")
    assert os.access(postinst, os.X_OK)
    unit = open(os.path.join(
        pkg, "usr", "lib", "systemd", "system",
        "elasticsearch-tpu.service")).read()
    assert "Type=notify" in unit            # sd_notify readiness
    assert "LimitMEMLOCK=infinity" in unit  # bootstrap.memory_lock root
    assert os.path.exists(os.path.join(
        pkg, "etc", "elasticsearch-tpu", "elasticsearch.yml"))
    assert os.path.exists(os.path.join(
        pkg, "usr", "share", "elasticsearch-tpu", "bin",
        "elasticsearch"))


def test_rpm_and_docker_staging(tmp_path):
    spec = distribution.write_rpm(str(tmp_path))
    text = open(spec).read()
    assert "Name: elasticsearch-tpu" in text
    assert "%files" in text and "%pre" in text
    dockerfile = distribution.write_docker(str(tmp_path / "d"))
    text = open(dockerfile).read()
    assert "EXPOSE 9200 9300" in text
    assert "USER 1000:1000" in text         # never root in the image
