"""Transport layer: wire format, RPC dispatch, handshake, timeouts,
QoS lanes, task manager (ref strategy: the reference unit-tests actions
over CapturingTransport/MockTransportService without sockets, and the
TCP stack with real loopback sockets — both covered here)."""

import threading
import time

import pytest

from elasticsearch_tpu.transport import (
    ConnectTransportException,
    DiscoveryNode,
    InProcessTransport,
    ReceiveTimeoutTransportException,
    RemoteTransportException,
    ResponseHandler,
    TcpTransport,
    TransportService,
    make_inprocess_cluster_registry,
)
from elasticsearch_tpu.transport.tasks import (
    CancellableTask,
    TaskCancelledException,
    TaskId,
    TaskManager,
)
from elasticsearch_tpu.transport.transport import (
    LANE_BULK,
    LANE_RECOVERY,
    LANE_REG,
    LANE_STATE,
    lane_for_action,
)
from elasticsearch_tpu.transport.wire import StreamInput, StreamOutput


# ---------------------------------------------------------------- wire

def test_wire_roundtrip_primitives():
    out = StreamOutput()
    out.write_vint(0)
    out.write_vint(127)
    out.write_vint(128)
    out.write_vint(3_000_000_000)
    out.write_zlong(-1)
    out.write_zlong(12345)
    out.write_zlong(-(2 ** 40))
    out.write_long(-42)
    out.write_double(3.5)
    out.write_bool(True)
    out.write_string("héllo wörld")
    out.write_optional_string(None)
    out.write_optional_string("x")
    out.write_obj({"a": [1, 2, {"b": None}]})
    sin = StreamInput(out.bytes())
    assert sin.read_vint() == 0
    assert sin.read_vint() == 127
    assert sin.read_vint() == 128
    assert sin.read_vint() == 3_000_000_000
    assert sin.read_zlong() == -1
    assert sin.read_zlong() == 12345
    assert sin.read_zlong() == -(2 ** 40)
    assert sin.read_long() == -42
    assert sin.read_double() == 3.5
    assert sin.read_bool() is True
    assert sin.read_string() == "héllo wörld"
    assert sin.read_optional_string() is None
    assert sin.read_optional_string() == "x"
    assert sin.read_obj() == {"a": [1, 2, {"b": None}]}
    assert sin.remaining() == 0


def test_wire_numpy_coercion():
    import numpy as np
    out = StreamOutput()
    out.write_obj({"v": np.int32(7), "a": np.arange(3)})
    assert StreamInput(out.bytes()).read_obj() == {"v": 7, "a": [0, 1, 2]}


# ------------------------------------------------- in-process transport

@pytest.fixture()
def pair():
    registry = make_inprocess_cluster_registry()
    nodes = []
    services = []
    for i in range(2):
        node = DiscoveryNode(node_id=f"node{i}", name=f"n{i}")
        svc = TransportService(InProcessTransport(node, registry))
        nodes.append(node)
        services.append(svc)
    yield nodes, services
    for svc in services:
        svc.close()


def test_request_response_roundtrip(pair):
    nodes, services = pair
    services[1].register_request_handler(
        "test:echo",
        lambda req, channel, src: channel.send_response(
            {"echo": req["msg"], "from": src.node_id}))
    services[0].connect_to_node(nodes[1])
    resp = services[0].send_request_sync(nodes[1], "test:echo",
                                         {"msg": "hi"}, timeout=5)
    assert resp == {"echo": "hi", "from": "node0"}


def test_remote_exception_propagates(pair):
    nodes, services = pair

    def boom(req, channel, src):
        raise ValueError("kapow")

    services[1].register_request_handler("test:boom", boom)
    services[0].connect_to_node(nodes[1])
    with pytest.raises(RemoteTransportException) as ei:
        services[0].send_request_sync(nodes[1], "test:boom", {}, timeout=5)
    assert "kapow" in str(ei.value)
    assert ei.value.remote_type == "ValueError"


def test_unknown_action_fails(pair):
    nodes, services = pair
    services[0].connect_to_node(nodes[1])
    with pytest.raises(RemoteTransportException, match="No handler"):
        services[0].send_request_sync(nodes[1], "test:nope", {}, timeout=5)


def test_local_short_circuit(pair):
    nodes, services = pair
    services[0].register_request_handler(
        "test:local", lambda req, ch, src: ch.send_response({"ok": 1}))
    # no connect needed for self
    resp = services[0].send_request_sync(nodes[0], "test:local", {},
                                         timeout=5)
    assert resp == {"ok": 1}


def test_timeout_fires(pair):
    nodes, services = pair
    services[1].register_request_handler(
        "test:blackhole", lambda req, ch, src: None)  # never responds
    services[0].connect_to_node(nodes[1])
    with pytest.raises(ReceiveTimeoutTransportException):
        services[0].send_request_sync(nodes[1], "test:blackhole", {},
                                      timeout=0.6)


def test_handshake_rejects_unknown_node():
    registry = make_inprocess_cluster_registry()
    node = DiscoveryNode(node_id="a", name="a")
    svc = TransportService(InProcessTransport(node, registry))
    try:
        ghost = DiscoveryNode(node_id="ghost", name="ghost")
        with pytest.raises(ConnectTransportException):
            svc.connect_to_node(ghost)
    finally:
        svc.close()


def test_connection_listener_events(pair):
    nodes, services = pair
    events = []
    services[0].add_connection_listener(
        lambda node, ev: events.append((node.node_id, ev)))
    services[0].connect_to_node(nodes[1])
    services[0].disconnect_from_node(nodes[1])
    assert events == [("node1", "connected"), ("node1", "disconnected")]


def test_interceptor_wraps_send_and_handle():
    registry = make_inprocess_cluster_registry()
    seen = []

    class Recorder:
        def intercept_sender(self, sender):
            def wrapped(node, action, request, handler, timeout=None):
                seen.append(("send", action))
                return sender(node, action, request, handler, timeout)
            return wrapped

        def intercept_handler(self, action, handler):
            def wrapped(req, channel, src):
                seen.append(("recv", action))
                return handler(req, channel, src)
            return wrapped

    nodes = [DiscoveryNode(node_id=f"i{i}", name=f"i{i}") for i in range(2)]
    services = [TransportService(InProcessTransport(n, registry),
                                 interceptors=[Recorder()]) for n in nodes]
    try:
        services[1].register_request_handler(
            "test:icpt", lambda r, c, s: c.send_response({}))
        services[0].connect_to_node(nodes[1])
        services[0].send_request_sync(nodes[1], "test:icpt", {}, timeout=5)
        assert ("send", "test:icpt") in seen
        assert ("recv", "test:icpt") in seen
    finally:
        for s in services:
            s.close()


# ------------------------------------------------------- tcp transport

def test_tcp_roundtrip_and_disconnect():
    a = DiscoveryNode(node_id="tcpa", name="tcpa", host="127.0.0.1")
    b = DiscoveryNode(node_id="tcpb", name="tcpb", host="127.0.0.1")
    ta = TcpTransport(a)
    tb = TcpTransport(b)
    sa = TransportService(ta)
    sb = TransportService(tb)
    try:
        sb.register_request_handler(
            "test:tcp-echo",
            lambda req, ch, src: ch.send_response(
                {"echo": req["x"], "src": src.node_id if src else None}))
        bound_b = tb.local_node
        sa.connect_to_node(bound_b)
        resp = sa.send_request_sync(bound_b, "test:tcp-echo", {"x": 41},
                                    timeout=5)
        assert resp["echo"] == 41
        assert resp["src"] == "tcpa"
        # big payload crosses frame/recv boundaries
        big = "y" * 300_000
        resp = sa.send_request_sync(bound_b, "test:tcp-echo", {"x": big},
                                    timeout=10)
        assert resp["echo"] == big
    finally:
        sa.close()
        sb.close()


def test_tcp_pending_fail_on_peer_death():
    a = DiscoveryNode(node_id="tA", name="tA", host="127.0.0.1")
    b = DiscoveryNode(node_id="tB", name="tB", host="127.0.0.1")
    ta, tb = TcpTransport(a), TcpTransport(b)
    sa, sb = TransportService(ta), TransportService(tb)
    try:
        sb.register_request_handler(
            "test:never", lambda req, ch, src: None)
        sa.connect_to_node(tb.local_node)
        failures = []
        done = threading.Event()
        sa.send_request(tb.local_node, "test:never", {},
                        ResponseHandler(lambda r: done.set(),
                                        lambda e: (failures.append(e),
                                                   done.set())),
                        timeout=1.0)
        # peer dies; timeout sweeper must fail the pending request
        sb.close()
        assert done.wait(5)
        assert failures
    finally:
        sa.close()


# ------------------------------------------------------------ QoS lanes

def test_lane_routing():
    assert lane_for_action("internal:index/shard/recovery/start") == LANE_RECOVERY
    assert lane_for_action("indices:data/write/bulk[s]") == LANE_BULK
    assert lane_for_action("internal:cluster/coordination/publish_state") == LANE_STATE
    assert lane_for_action("indices:data/read/search[phase/query]") == LANE_REG


# --------------------------------------------------------------- tasks

def test_task_register_list_unregister():
    tm = TaskManager("nodeX")
    t = tm.register("transport", "indices:data/read/search", "desc")
    assert tm.get_task(t.id) is t
    listed = tm.list_tasks("indices:data/read/*")
    assert [x.id for x in listed] == [t.id]
    assert tm.list_tasks("cluster:*") == []
    d = t.to_dict("nodeX")
    assert d["action"] == "indices:data/read/search"
    assert d["cancellable"] is False
    tm.unregister(t)
    assert tm.get_task(t.id) is None


def test_cancellable_task_cooperative():
    tm = TaskManager("nodeX")
    t = tm.register("transport", "a", cancellable=True)
    assert isinstance(t, CancellableTask)
    t.ensure_not_cancelled()
    fired = []
    t.add_cancellation_listener(lambda: fired.append(1))
    tm.cancel(t, "test reason")
    assert fired == [1]
    with pytest.raises(TaskCancelledException):
        t.ensure_not_cancelled()
    # listener added after cancellation fires immediately
    t.add_cancellation_listener(lambda: fired.append(2))
    assert fired == [1, 2]


def test_ban_propagation_to_late_children():
    tm = TaskManager("nodeX")
    parent = tm.register("transport", "parent", cancellable=True)
    child_before = tm.register(
        "transport", "child", parent_task_id=TaskId("nodeX", parent.id),
        cancellable=True)
    tm.cancel(parent, "going away")
    assert child_before.is_cancelled()
    # a child arriving after the ban is cancelled on registration
    child_after = tm.register(
        "transport", "child2", parent_task_id=TaskId("nodeX", parent.id),
        cancellable=True)
    assert child_after.is_cancelled()


def test_task_scope_context_manager():
    tm = TaskManager("n")
    with tm.task_scope("transport", "scoped") as t:
        assert tm.get_task(t.id) is t
    assert tm.get_task(t.id) is None
