"""Native hardening shim (ref: bootstrap/SystemCallFilter.java — the
seccomp BPF filter denying process-spawning syscalls with EACCES;
bootstrap/JNANatives.java — mlockall; BootstrapChecks.MlockallCheck /
SystemCallFilterCheck). The filter is IRREVERSIBLE for a process, so
every install happens in a disposable subprocess."""

import os
import subprocess
import sys
import textwrap

import pytest

from elasticsearch_tpu import native
from elasticsearch_tpu.common import bootstrap
from elasticsearch_tpu.common.settings import Settings

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT})


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_syscall_filter_blocks_exec_and_fork():
    r = _run("""
        import ctypes, errno, os, subprocess, sys
        from elasticsearch_tpu import native
        rc = native.install_system_call_filter()
        assert rc in (0, 1), rc
        # execve is denied with EACCES (ref: SystemCallFilter's BPF
        # returns SECCOMP_RET_ERRNO|EACCES)
        try:
            subprocess.run(["/bin/true"])
            sys.exit("subprocess unexpectedly spawned")
        except (PermissionError, OSError) as e:
            assert getattr(e, "errno", errno.EACCES) in (
                errno.EACCES, errno.EPERM), e
        import platform
        if rc == 0 and platform.machine() == "x86_64":
            # the raw fork syscall is denied (glibc's fork() wrapper
            # rides clone(), which must stay open for threads — the
            # reference's filter has the same shape: a cloned child
            # still cannot execve, which is the property that matters)
            import ctypes
            libc = ctypes.CDLL(None, use_errno=True)
            if hasattr(libc, "syscall"):
                res = libc.syscall(57)     # __NR_fork, x86_64 only
                assert res == -1, res
                assert ctypes.get_errno() in (errno.EACCES,
                                              errno.EPERM)
        # ordinary syscalls still work after the filter
        with open("/proc/self/status") as fh:
            assert "Seccomp" in fh.read()
        print("FILTER-OK", rc)
    """)
    assert "FILTER-OK" in r.stdout, (r.stdout, r.stderr)


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_mlockall_returns_status():
    r = _run("""
        from elasticsearch_tpu import native
        rc = native.try_mlockall()
        assert isinstance(rc, int), rc
        if rc == 0:
            with open("/proc/self/status") as fh:
                locked = [l for l in fh if l.startswith("VmLck")]
            assert locked, "mlockall reported success but VmLck missing"
        print("MLOCK-STATUS", rc)
    """)
    assert "MLOCK-STATUS" in r.stdout, (r.stdout, r.stderr)


def test_bootstrap_checks_wire_native_status():
    r = _run("""
        from elasticsearch_tpu.common import bootstrap
        from elasticsearch_tpu.common.settings import Settings
        # memory_lock requested but not achieved -> check failure in
        # production mode (ref: BootstrapChecks.MlockallCheck)
        bootstrap.NATIVE_STATUS.update(
            attempted=True, memory_locked=False,
            system_call_filter_installed=True)
        s = Settings.from_dict({
            "bootstrap": {"memory_lock": True},
            "discovery": {"seed_hosts": "10.0.0.1"}})
        msgs = bootstrap.run_bootstrap_checks(s, "127.0.0.1")
        assert any("memory is not locked" in m for m in msgs), msgs
        # filter requested (default true) but failed -> failure
        bootstrap.NATIVE_STATUS.update(
            memory_locked=True, system_call_filter_installed=False)
        msgs = bootstrap.run_bootstrap_checks(s, "127.0.0.1")
        assert any("system call filters failed" in m for m in msgs), msgs
        # explicit opt-out silences it (bootstrap.system_call_filter
        # false at your own risk)
        s2 = Settings.from_dict({
            "bootstrap": {"system_call_filter": False},
            "discovery": {"seed_hosts": "10.0.0.1"}})
        msgs = bootstrap.run_bootstrap_checks(s2, "127.0.0.1")
        assert not any("system call" in m for m in msgs), msgs
        # both achieved -> clean
        bootstrap.NATIVE_STATUS.update(
            memory_locked=True, system_call_filter_installed=True)
        msgs = bootstrap.run_bootstrap_checks(s, "127.0.0.1")
        assert not any("memory is not locked" in m
                       or "system call" in m for m in msgs), msgs
        print("CHECKS-OK")
    """)
    assert "CHECKS-OK" in r.stdout, (r.stdout, r.stderr)


def test_initialize_natives_applies_settings():
    """initialize_natives + a live node under the filter: the launcher
    path installs seccomp, then the node still boots and serves."""
    r = _run("""
        import json, tempfile, urllib.request
        from elasticsearch_tpu.common.bootstrap import (NATIVE_STATUS,
                                                        initialize_natives)
        from elasticsearch_tpu.common.settings import Settings
        s = Settings.from_dict({"bootstrap": {"memory_lock": False},
                                "http": {"native": False}})
        st = initialize_natives(s)
        assert st["attempted"]
        assert st["system_call_filter_installed"], st
        from elasticsearch_tpu.node import Node
        node = Node(settings=s, data_path=tempfile.mkdtemp() + "/d")
        port = node.start(0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=30) as resp:
            assert json.loads(resp.read())["tagline"]
        node.close()
        print("NODE-UNDER-FILTER-OK")
    """)
    assert "NODE-UNDER-FILTER-OK" in r.stdout, (r.stdout, r.stderr)
