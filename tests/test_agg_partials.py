"""Shard-invariance property suite for mergeable aggregation partials
(search/agg_partials.py): merge-of-N-shard-partials must equal the
single-node result on a seeded corpus across RANDOM shard splits, for
every supported agg type — the InternalAggregationTestCase
reduce-correctness discipline, chaos-seeded so any red run replays
with ``--chaos-seed=N``.

Also pins: the digest error bound above the centroid budget, the
incremental consumer's batching/breaker/metrics contract, composite's
truncated-page exactness, the typed rejection of unsupported agg
types, and the device kernel parity of ops/aggs.py (thresholds forced
to zero so the scatter/fused paths run under CPU jax).
"""

import copy
import json

import numpy as np
import pytest

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.index.service import IndicesService
from elasticsearch_tpu.search import agg_partials as AP
from elasticsearch_tpu.search import aggregations as A
from elasticsearch_tpu.search.service import SearchService
from elasticsearch_tpu.search.sketches import TDigest

MAPPINGS = {"properties": {
    "category": {"type": "keyword"},
    "price": {"type": "double"},
    "qty": {"type": "long"},
    "sold_at": {"type": "date"},
}}


def make_docs(rng, n=150):
    cats = ["alpha", "beta", "gamma", "delta", "epsilon"]
    docs = []
    for i in range(n):
        d = {"category": cats[int(rng.integers(0, len(cats)))],
             "sold_at": f"2021-03-{int(rng.integers(1, 28)):02d}"}
        if rng.random() > 0.1:          # ~10% missing price
            d["price"] = float(np.round(rng.uniform(1, 100), 2))
        if rng.random() > 0.2:
            d["qty"] = int(rng.integers(1, 50))
        docs.append(d)
    return docs


def build_split(tmp_path, docs, assign, n_shards):
    """One single-shard 'truth' index plus n_shards disjoint 'shard'
    indices holding the same docs split by ``assign``."""
    indices = IndicesService(str(tmp_path / "data"))
    full = indices.create_index("full", {"index.number_of_shards": 1},
                                MAPPINGS)
    shards = [indices.create_index(f"s{i}",
                                   {"index.number_of_shards": 1},
                                   MAPPINGS)
              for i in range(n_shards)]
    for i, d in enumerate(docs):
        full.index_doc(str(i), d)
        shards[assign[i]].index_doc(str(i), d)
    full.refresh()
    for s in shards:
        s.refresh()
    return indices


def shard_partials(indices, spec, n_shards):
    out = []
    for i in range(n_shards):
        index = indices.get(f"s{i}")
        ctx = []
        for s in index.shard_searchers():
            for seg in s.segments:
                ctx.append((seg, seg.live.copy(), index.mapper))
        out.append(AP.collect_partials(spec, ctx, index.mapper,
                                       index.device_cache))
    return out


# the full supported distributed surface, sub-aggs and pipelines
# included (metric + bucket + sibling pipeline + parent pipeline)
FULL_SPEC = {
    "by_cat": {"terms": {"field": "category"},
               "aggs": {"avg_p": {"avg": {"field": "price"}},
                        "pct": {"percentiles": {
                            "field": "price", "percents": [50.0]}},
                        "cum": {"cumulative_sum": {
                            "buckets_path": "avg_p"}}}},
    "rare": {"rare_terms": {"field": "category", "max_doc_count": 100}},
    "days": {"date_histogram": {"field": "sold_at",
                                "calendar_interval": "day"},
             "aggs": {"rev": {"sum": {"field": "price"}},
                      "card": {"cardinality": {"field": "category"}},
                      "cumcard": {"cumulative_cardinality": {
                          "buckets_path": "card"}},
                      "deriv": {"derivative": {"buckets_path": "rev"}},
                      "pp": {"percentiles": {"field": "price",
                                             "percents": [50.0]}},
                      "movp": {"moving_percentiles": {
                          "buckets_path": "pp", "window": 3}}}},
    "hist": {"histogram": {"field": "price", "interval": 20.0},
             "aggs": {"st": {"stats": {"field": "qty"}},
                      "est": {"extended_stats": {"field": "qty"}}}},
    "pct_all": {"percentiles": {"field": "price",
                                "percents": [5.0, 50.0, 95.0]}},
    "ranks": {"percentile_ranks": {"field": "price",
                                   "values": [25.0, 75.0]}},
    "card": {"cardinality": {"field": "category"}},
    "est": {"extended_stats": {"field": "price"}},
    "vc": {"value_count": {"field": "qty"}},
    "mn": {"min": {"field": "price"}},
    "mx": {"max": {"field": "price"}},
    "s": {"sum": {"field": "qty"}},
    "avg_missing": {"avg": {"field": "price", "missing": 0.0}},
    "w": {"weighted_avg": {"value": {"field": "price"},
                           "weight": {"field": "qty"}}},
    "mad": {"median_absolute_deviation": {"field": "price"}},
    "box": {"boxplot": {"field": "price"}},
    "rng": {"range": {"field": "price",
                      "ranges": [{"to": 30.0}, {"from": 30.0}]},
            "aggs": {"m": {"max": {"field": "qty"}}}},
    "dr": {"date_range": {"field": "sold_at", "ranges": [
        {"from": 1614556800000}, {"to": 1614556800000}]}},
    "comp": {"composite": {"size": 6, "sources": [
        {"cat": {"terms": {"field": "category"}}},
        {"p": {"histogram": {"field": "price", "interval": 50.0}}}],
    }, "aggs": {"m": {"min": {"field": "qty"}}}},
    "top": {"top_hits": {"size": 3,
                         "sort": [{"price": {"order": "desc"}}]}},
    "glob": {"global": {}, "aggs": {"n": {"value_count": {
        "field": "qty"}}}},
    "miss": {"missing": {"field": "qty"}},
    "flt": {"filter": {"term": {"category": "alpha"}},
            "aggs": {"mx": {"max": {"field": "price"}}}},
    "flts": {"filters": {"filters": {
        "big": {"range": {"price": {"gte": 50}}},
        "small": {"range": {"price": {"lt": 50}}}}}},
    "scripted": {"scripted_metric": {
        "init_script": "state.n = 0;",
        "map_script": "state.n += 1;",
        "combine_script": "return state.n;",
        "reduce_script":
            "double t = 0; for (def s : states) { t += s } return t;"}},
    "avg_of_avg": {"avg_bucket": {"buckets_path": "by_cat>avg_p"}},
    "pb": {"percentiles_bucket": {"buckets_path": "days>rev",
                                  "percents": [50.0]}},
}


def assert_agg_equal(a, b, path="", rel=1e-9):
    """Structural equality with float tolerance (merge order only moves
    float-summation rounding)."""
    if isinstance(a, dict) and isinstance(b, dict):
        assert set(a) == set(b), f"{path}: keys {set(a) ^ set(b)}"
        for k in a:
            assert_agg_equal(a[k], b[k], f"{path}.{k}", rel)
    elif isinstance(a, list) and isinstance(b, list):
        assert len(a) == len(b), f"{path}: {len(a)} != {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            assert_agg_equal(x, y, f"{path}[{i}]", rel)
    elif isinstance(a, float) or isinstance(b, float):
        assert a is not None and b is not None, f"{path}: {a} vs {b}"
        assert abs(a - b) <= rel * max(1.0, abs(a)), \
            f"{path}: {a} != {b}"
    else:
        assert a == b, f"{path}: {a!r} != {b!r}"


@pytest.mark.chaos(seed=101)
@pytest.mark.parametrize("case", range(3))
def test_shard_invariance_every_supported_type(tmp_path, chaos_seed,
                                               case):
    """merge(collect(shard_i)) == single-node for the ENTIRE supported
    agg surface, across a random split, random merge order, and a
    random reduce batch size."""
    rng = np.random.default_rng(chaos_seed + 1000 * case)
    docs = make_docs(rng)
    n_shards = int(rng.integers(2, 5))
    assign = rng.integers(0, n_shards, len(docs))
    indices = build_split(tmp_path, docs, assign, n_shards)
    try:
        svc = SearchService(indices)
        single = svc.search("full",
                            {"size": 0, "aggs": FULL_SPEC})["aggregations"]
        parts = shard_partials(indices, FULL_SPEC, n_shards)
        for p in parts:
            json.dumps(p)        # the wire contract: pure JSON
        order = rng.permutation(n_shards)
        cons = AP.AggReduceConsumer(FULL_SPEC,
                                    batch_size=int(rng.integers(2, 4)))
        for i in order:
            cons.consume(copy.deepcopy(parts[i]))
        acc, phases = cons.finish()
        out = AP.strip_internal(AP.finalize_partials(FULL_SPEC, acc))
        assert phases >= 1
        assert_agg_equal(single, out, path=f"seed={chaos_seed}")
    finally:
        indices.close()


@pytest.mark.chaos(seed=77)
def test_digest_error_bound_above_budget(chaos_seed):
    """Above the centroid budget the merged sketch is approximate with
    the documented bound: quantile error ≤ ~1% of rank (q-space) at
    compression 256, for any shard split."""
    rng = np.random.default_rng(chaos_seed)
    values = np.concatenate([rng.normal(0, 1, 20_000),
                             rng.exponential(5, 20_000)])
    parts = np.array_split(rng.permutation(values), 7)
    merged = TDigest.merge_all([TDigest.from_values(p) for p in parts])
    assert merged.means.size <= merged.compression
    for q in (1, 5, 25, 50, 75, 95, 99):
        est = merged.quantile(q)
        q_err = abs(float((values <= est).mean()) * 100.0 - q)
        assert q_err < 1.0, f"seed={chaos_seed}: q={q} err={q_err}"
    # wire form round-trips bit-exact
    clone = TDigest.from_wire(merged.to_wire())
    assert clone.quantile(50) == merged.quantile(50)


def test_consumer_batching_breaker_and_metrics():
    """The QueryPhaseResultConsumer contract: reduce every batch_size
    arrivals (memory ≤ one batch + accumulator), charge buffered bytes
    to the request breaker and release them at each reduce, surface
    search.agg_reduce.* metrics, count the final phase."""
    from elasticsearch_tpu.telemetry import MetricsRegistry
    from elasticsearch_tpu.utils.breaker import CircuitBreaker
    spec = {"s": {"sum": {"field": "x"}}}
    parts = [{"s": {"n": 1, "s": float(i), "mn": float(i),
                    "mx": float(i), "ss": float(i * i)}}
             for i in range(7)]
    breaker = CircuitBreaker("request", limit_bytes=10_000)
    metrics = MetricsRegistry()
    cons = AP.AggReduceConsumer(spec, batch_size=3, breaker=breaker,
                                metrics=metrics)
    for p in parts:
        cons.consume(p)
    # 7 partials → two full batches reduced, one remainder buffered
    assert cons.num_reduce_phases == 2
    assert len(cons.buffer) == 1
    assert breaker.used > 0          # the buffered remainder is charged
    acc, phases = cons.finish()
    assert phases == 4               # 2 partial + 1 remainder + 1 final
    assert breaker.used == 0         # everything released
    out = AP.finalize_partials(spec, acc)
    assert out["s"]["value"] == pytest.approx(sum(range(7)))
    m = metrics.to_dict()
    assert m["search.agg_reduce.partials"]["value"] == 7
    assert m["search.agg_reduce.batches"]["value"] == 3
    assert any(k.startswith("search.agg_reduce.latency") for k in m)

    # a breaker too small to buffer one partial trips out of consume
    tiny = CircuitBreaker("request", limit_bytes=8)
    cons2 = AP.AggReduceConsumer(spec, batch_size=3, breaker=tiny)
    with pytest.raises(Exception) as ei:
        cons2.consume(parts[0])
    assert "circuit" in type(ei.value).__name__.lower() \
        or "breaking" in str(ei.value).lower()

    # failure-path seam: close() releases buffered charge WITHOUT a
    # reduce (a search completing with an error must never leave
    # partial bytes charged for the process lifetime), idempotently
    b3 = CircuitBreaker("request", limit_bytes=10_000)
    cons3 = AP.AggReduceConsumer(spec, batch_size=10, breaker=b3)
    cons3.consume(parts[0])
    cons3.consume(parts[1])
    assert b3.used > 0
    cons3.close()
    assert b3.used == 0
    cons3.close()                      # idempotent
    cons3.consume(parts[2])            # finished: dropped, not charged
    assert b3.used == 0


def test_check_distributed_support_rejects_typed():
    AP.check_distributed_support(FULL_SPEC)     # whole surface passes
    with pytest.raises(IllegalArgumentException) as ei:
        AP.check_distributed_support(
            {"sig": {"significant_terms": {"field": "category"}}})
    assert "distributed" in str(ei.value)
    with pytest.raises(IllegalArgumentException):
        AP.check_distributed_support(
            {"ok": {"terms": {"field": "category"},
                    "aggs": {"bad": {"sampler": {}}}}})


@pytest.mark.chaos(seed=202)
def test_composite_truncated_paging_stays_exact(tmp_path, chaos_seed):
    """Exact paging under shard truncation: with page sizes smaller
    than the shard key space, walking the distributed composite via
    after_key visits exactly the single-node key sequence with exact
    doc counts (the reduce never emits a key past a truncated shard's
    last reported key)."""
    rng = np.random.default_rng(chaos_seed)
    docs = make_docs(rng, n=120)
    n_shards = 3
    assign = rng.integers(0, n_shards, len(docs))
    indices = build_split(tmp_path, docs, assign, n_shards)
    try:
        svc = SearchService(indices)
        base = {"composite": {"size": 3, "sources": [
            {"cat": {"terms": {"field": "category"}}},
            {"p": {"histogram": {"field": "price", "interval": 10.0}}}]}}
        single_pages = []
        after = None
        while True:
            spec = copy.deepcopy(base)
            if after is not None:
                spec["composite"]["after"] = after
            r = svc.search("full", {"size": 0,
                                    "aggs": {"c": spec}})["aggregations"]
            buckets = r["c"]["buckets"]
            if not buckets:
                break
            single_pages.extend(
                (json.dumps(b["key"], sort_keys=True), b["doc_count"])
                for b in buckets)
            after = r["c"].get("after_key")
            if after is None:
                break
        dist_pages = []
        after = None
        for _ in range(200):        # bounded: every page must advance
            spec = copy.deepcopy(base)
            if after is not None:
                spec["composite"]["after"] = after
            parts = shard_partials(indices, {"c": spec}, n_shards)
            acc = None
            for p in parts:
                acc = AP.merge_partials({"c": spec}, acc, p)
            out = AP.finalize_partials({"c": spec}, acc)
            buckets = out["c"]["buckets"]
            if not buckets:
                break
            dist_pages.extend(
                (json.dumps(b["key"], sort_keys=True), b["doc_count"])
                for b in buckets)
            after = out["c"].get("after_key")
            if after is None:
                break
        assert dist_pages == single_pages, f"seed={chaos_seed}"
    finally:
        indices.close()


@pytest.mark.chaos(seed=303)
def test_terms_shard_size_trim_error_accounting(tmp_path, chaos_seed):
    """An explicit shard_size trims shard partials with ES error
    accounting: counts may undercount by at most
    doc_count_error_upper_bound, and sum_other_doc_count absorbs the
    dropped mass."""
    rng = np.random.default_rng(chaos_seed)
    docs = make_docs(rng, n=200)
    n_shards = 4
    assign = rng.integers(0, n_shards, len(docs))
    indices = build_split(tmp_path, docs, assign, n_shards)
    try:
        svc = SearchService(indices)
        spec = {"t": {"terms": {"field": "category", "size": 2,
                                "shard_size": 2}}}
        truth = svc.search(
            "full", {"size": 0, "aggs": {
                "t": {"terms": {"field": "category",
                                "size": 2}}}})["aggregations"]
        parts = shard_partials(indices, spec, n_shards)
        acc = None
        for p in parts:
            acc = AP.merge_partials(spec, acc, p)
        out = AP.finalize_partials(spec, acc)
        err = out["t"]["doc_count_error_upper_bound"]
        assert err >= 0
        truth_counts = {b["key"]: b["doc_count"]
                        for b in truth["t"]["buckets"]}
        for b in out["t"]["buckets"]:
            true_c = truth_counts.get(b["key"])
            if true_c is not None:
                assert b["doc_count"] <= true_c \
                    and b["doc_count"] >= true_c - err, \
                    f"seed={chaos_seed}: {b} vs {true_c} (err {err})"
        # total mass is conserved: buckets + other == all counted docs
        total = sum(b["doc_count"] for b in out["t"]["buckets"]) \
            + out["t"]["sum_other_doc_count"]
        assert total == sum(1 for d in docs if "category" in d)
    finally:
        indices.close()


@pytest.mark.chaos(seed=404)
def test_device_kernel_parity_forced(tmp_path, chaos_seed,
                                     monkeypatch):
    """Force DEVICE_AGG_MIN_DOCS to 0 so the device metric/histogram
    kernels (ops/aggs.py masked_metric_stats / bucket scatter-add)
    actually dispatch under CPU jax — results must match the exact
    host path within f32 tolerance (counts/min/max exact)."""
    rng = np.random.default_rng(chaos_seed)
    docs = make_docs(rng, n=150)
    indices = build_split(tmp_path, docs, np.zeros(len(docs), int), 1)
    try:
        svc = SearchService(indices)
        spec = {
            "st": {"stats": {"field": "price"}},
            "est": {"extended_stats": {"field": "price"}},
            "hist": {"histogram": {"field": "price", "interval": 10.0},
                     "aggs": {"q": {"stats": {"field": "qty"}}}},
        }
        host = svc.search("full", {"size": 0,
                                   "aggs": spec})["aggregations"]
        monkeypatch.setattr(A, "DEVICE_AGG_MIN_DOCS", 0)
        index = indices.get("full")
        ctx = []
        for s in index.shard_searchers():
            for seg in s.segments:
                ctx.append((seg, seg.live.copy(), index.mapper))
        dev = A.compute_aggs(spec, ctx, index.mapper,
                             index.device_cache)
        # counts and extrema are exact on device; sums ride f32
        assert dev["st"]["count"] == host["st"]["count"]
        assert dev["st"]["min"] == pytest.approx(host["st"]["min"],
                                                 rel=1e-6)
        assert dev["st"]["max"] == pytest.approx(host["st"]["max"],
                                                 rel=1e-6)
        assert dev["st"]["sum"] == pytest.approx(host["st"]["sum"],
                                                 rel=1e-4)
        assert dev["est"]["variance"] == pytest.approx(
            host["est"]["variance"], rel=1e-3)
        hb, db = host["hist"]["buckets"], dev["hist"]["buckets"]
        assert [(b["key"], b["doc_count"]) for b in hb] == \
               [(b["key"], b["doc_count"]) for b in db]
        for b1, b2 in zip(hb, db):
            assert b2["q"]["count"] == b1["q"]["count"]
            if b1["q"]["count"]:
                assert b2["q"]["sum"] == pytest.approx(b1["q"]["sum"],
                                                       rel=1e-4)
    finally:
        indices.close()


def test_host_fallback_formulas_pinned(tmp_path):
    """Below DEVICE_AGG_MIN_DOCS the host path runs the pre-existing
    numpy formulas bit-for-bit: pin them against direct numpy over the
    corpus (the device dispatch must never leak into small segments)."""
    rng = np.random.default_rng(5)
    docs = make_docs(rng, n=80)
    indices = build_split(tmp_path, docs, np.zeros(len(docs), int), 1)
    try:
        svc = SearchService(indices)
        out = svc.search("full", {"size": 0, "aggs": {
            "st": {"stats": {"field": "price"}},
            "pct": {"percentiles": {"field": "price",
                                    "percents": [50.0]}},
        }})["aggregations"]
        prices = np.asarray([d["price"] for d in docs
                             if "price" in d])
        assert out["st"]["sum"] == float(prices.sum())        # exact
        assert out["st"]["avg"] == float(prices.mean())       # exact
        assert out["st"]["min"] == float(prices.min())
        assert out["st"]["max"] == float(prices.max())
        assert out["pct"]["values"]["50.0"] == \
            float(np.percentile(prices, 50.0))                # exact
    finally:
        indices.close()


def test_agg_reduce_metrics_surface_in_nodes_stats(tmp_path):
    """The search.agg_reduce.* counters/histograms appear in the
    telemetry section of GET /_nodes/stats after a search with aggs
    (single-node: one batch, family "_all"; the distributed consumer
    feeds the same names per family)."""
    from elasticsearch_tpu.node import Node
    node = Node(data_path=str(tmp_path / "n1"))
    try:
        rc = node.rest_controller
        status, _ = rc.dispatch("PUT", "/shop", {}, {
            "mappings": {"properties": {
                "category": {"type": "keyword"},
                "price": {"type": "double"}}}})
        assert status < 400
        for i, (c, p) in enumerate([("a", 1.0), ("b", 2.0),
                                    ("a", 3.0)]):
            status, _ = rc.dispatch(
                "PUT", f"/shop/_doc/{i}", {},
                {"category": c, "price": p})
            assert status < 400
        rc.dispatch("POST", "/shop/_refresh", {}, None)
        status, resp = rc.dispatch("POST", "/shop/_search", {}, {
            "size": 0, "aggs": {
                "cats": {"terms": {"field": "category"}},
                "avg": {"avg": {"field": "price"}}}})
        assert status < 400 and "aggregations" in resp
        status, stats = rc.dispatch("GET", "/_nodes/stats", {}, None)
        assert status < 400
        (node_stats,), = [list(stats["nodes"].values())]
        metrics = node_stats["telemetry"]["metrics"]
        assert metrics["search.agg_reduce.partials"]["value"] >= 1
        assert metrics["search.agg_reduce.batches"]["value"] >= 1
        assert any(k.startswith("search.agg_reduce.latency")
                   for k in metrics)
    finally:
        node.close()


def test_empty_value_source_shapes_match_single_node(tmp_path):
    """A query matching nothing must produce the SAME response shapes
    on both paths (review fix: distributed empty percentiles returned
    null-filled values where single-node returns {})."""
    rng = np.random.default_rng(9)
    docs = make_docs(rng, n=40)
    n_shards = 2
    assign = rng.integers(0, n_shards, len(docs))
    indices = build_split(tmp_path, docs, assign, n_shards)
    try:
        svc = SearchService(indices)
        spec = {
            "pct": {"percentiles": {"field": "price"}},
            "ranks": {"percentile_ranks": {"field": "price",
                                           "values": [5.0]}},
            "mad": {"median_absolute_deviation": {"field": "price"}},
            "box": {"boxplot": {"field": "price"}},
            "st": {"stats": {"field": "price"}},
            "est": {"extended_stats": {"field": "price"}},
            "s": {"sum": {"field": "price"}},
        }
        single = svc.search("full", {
            "size": 0, "query": {"term": {"category": "nope"}},
            "aggs": spec})["aggregations"]
        parts = []
        for i in range(n_shards):
            index = indices.get(f"s{i}")
            ctx = []
            for s in index.shard_searchers():
                for seg in s.segments:
                    ctx.append((seg, np.zeros(seg.n_docs, bool),
                                index.mapper))
            parts.append(AP.collect_partials(spec, ctx, index.mapper))
        acc = None
        for p in parts:
            acc = AP.merge_partials(spec, acc, p)
        out = AP.strip_internal(AP.finalize_partials(spec, acc))
        assert_agg_equal(single, out)
    finally:
        indices.close()


def test_mixed_keyword_numeric_terms_merge_never_crashes():
    """Multi-index mapping skew: field `f` keyword on one shard,
    numeric on another. The merged terms must render without float()
    crashing on keyword keys (review fix)."""
    spec = {"t": {"terms": {"field": "f"}}}
    kw = {"t": {"numeric": False,
                "terms": {"apple": {"c": 3}, "pear": {"c": 1}},
                "other": 0, "err": 0}}
    num = {"t": {"numeric": True,
                 "terms": {"7.0": {"c": 2}}, "other": 0, "err": 0}}
    acc = AP.merge_partials(spec, None, kw)
    acc = AP.merge_partials(spec, acc, num)
    out = AP.finalize_partials(spec, acc)
    keys = [b["key"] for b in out["t"]["buckets"]]
    assert "apple" in keys and 7 in keys
    counts = {b["key"]: b["doc_count"] for b in out["t"]["buckets"]}
    assert counts["apple"] == 3 and counts[7] == 2


def test_histogram_gap_fill_bucket_cap_both_paths(tmp_path):
    """One sparse value pair must raise a typed too-many-buckets error
    instead of materializing a 10^10-element gap fill — on the
    single-node path AND the distributed finalize (review fix)."""
    docs = [{"price": 0.0}, {"price": 1e10}]
    indices = build_split(tmp_path, docs, np.zeros(2, int), 1)
    try:
        svc = SearchService(indices)
        spec = {"h": {"histogram": {"field": "price", "interval": 1.0}}}
        with pytest.raises(IllegalArgumentException) as ei:
            svc.search("full", {"size": 0, "aggs": spec})
        assert "buckets" in str(ei.value)
        parts = shard_partials(indices, spec, 1)
        acc = AP.merge_partials(spec, None, parts[0])
        with pytest.raises(IllegalArgumentException):
            AP.finalize_partials(spec, acc)
    finally:
        indices.close()
