"""Multi-chip serving: the mesh backend on an 8-virtual-device CPU mesh
(conftest pins ``--xla_force_host_platform_device_count=8``).

The serving contract under test (ISSUE 9 acceptance): a mesh-served
`_search` is BYTE-identical to the single-device per-shard loop for the
pinned query mix (bm25, bool, knn) — scores, doc order, totals — and
every ineligible shape falls back CLEANLY (no error, typed
``fallback.<reason>`` counter) to the loop: one device, over-ceiling
corpora, dfs statistics, disabled backend. Plus the replica-axis cohort
fan-out (search/batching.py) and the `GET /_kernels` mesh surface
(dispatch counters + per-device residency) and per-chip profile
attribution."""

import numpy as np
import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import Node

VOCAB = ["amber", "basalt", "cedar", "dune", "ember", "fjord", "granite",
         "harbor", "islet", "juniper", "krill", "lagoon", "mesa", "nectar"]

DIMS = 8


@pytest.fixture
def node(tmp_path):
    n = Node(Settings.EMPTY, data_path=str(tmp_path / "data"))
    yield n
    n.close()


def do(node, method, path, params=None, body=None, expect=200):
    status, resp = node.rest_controller.dispatch(method, path, params, body)
    assert status == expect, f"{method} {path} -> {status}: {resp}"
    return resp


def seed(node, index, n_shards, n_docs=120, seed=5, forcemerge=True):
    rng = np.random.default_rng(seed)
    do(node, "PUT", f"/{index}", body={
        "settings": {"index": {"number_of_shards": n_shards}},
        "mappings": {"properties": {
            "title": {"type": "text"},
            "tag": {"type": "keyword"},
            "vec": {"type": "dense_vector", "dims": DIMS,
                    "similarity": "cosine"}}}})
    for i in range(n_docs):
        do(node, "PUT", f"/{index}/_doc/{i}",
           body={"title": " ".join(rng.choice(VOCAB, rng.integers(2, 10))),
                 "tag": str(rng.choice(["x", "y"])),
                 "vec": rng.standard_normal(DIMS).astype(
                     np.float32).tolist()},
           expect=201)
    do(node, "POST", f"/{index}/_refresh")
    if forcemerge:
        # one segment per shard — the mesh residency model
        do(node, "POST", f"/{index}/_forcemerge")
    return rng


def pinned_mix(rng):
    """The acceptance mix: bm25 match, bool (+filter, +msm), knn."""
    return [
        {"query": {"match": {"title": "amber dune"}}, "size": 50},
        {"query": {"match": {"title": {"query": "cedar fjord mesa",
                                       "operator": "and"}}}, "size": 50},
        {"query": {"bool": {
            "must": [{"match": {"title": "granite"}}],
            "filter": [{"term": {"tag": "x"}}]}}, "size": 50},
        {"query": {"bool": {
            "should": [{"match": {"title": "krill"}},
                       {"match": {"title": "lagoon harbor"}}],
            "minimum_should_match": 1}}, "size": 50},
        {"knn": {"field": "vec",
                 "query_vector": rng.standard_normal(DIMS).tolist(),
                 "k": 20, "num_candidates": 64},
         "_source": False, "size": 20},
    ]


def hits_of(r):
    return [(h["_id"], h["_score"]) for h in r["hits"]["hits"]]


def mesh_vs_loop(node, index, body, monkeypatch):
    """(mesh response, loop response, mesh_engaged) for one body."""
    svc = node.search_service
    before = svc.mesh_executor.mesh_searches
    r_mesh = do(node, "POST", f"/{index}/_search", body=dict(body))
    engaged = svc.mesh_executor.mesh_searches - before
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    try:
        r_loop = do(node, "POST", f"/{index}/_search", body=dict(body))
    finally:
        monkeypatch.delenv("ESTPU_MESH_SERVING")
    return r_mesh, r_loop, engaged


# ---------------------------------------------------------------- parity


def test_pinned_mix_byte_identical(node, monkeypatch):
    """ACCEPTANCE: the pinned bm25/bool/knn mix on an 8-device mesh is
    byte-identical — raw float scores, doc order, totals — to the
    per-shard loop, and every body actually engaged the mesh."""
    rng = seed(node, "m8", n_shards=8)
    for body in pinned_mix(rng):
        r_mesh, r_loop, engaged = mesh_vs_loop(node, "m8", body,
                                               monkeypatch)
        assert engaged == 1, body
        assert hits_of(r_mesh) == hits_of(r_loop), body
        assert r_mesh["hits"]["total"] == r_loop["hits"]["total"], body
        assert r_mesh["hits"]["max_score"] == \
            r_loop["hits"]["max_score"], body


@pytest.mark.chaos(seed=17)
def test_chaos_seeded_parity_with_deletes(node, monkeypatch, chaos_seed):
    """Chaos-seeded sweep: random corpus, random query mix, random
    DELETES (live-mask refresh on the resident corpus) — every round
    stays byte-identical to the loop. Replays with --chaos-seed=N."""
    rng = seed(node, "cx", n_shards=4, n_docs=90, seed=chaos_seed)
    # random deletes flip live bits only — the mesh refreshes live
    # bitmaps in place (postings stay resident)
    victims = rng.choice(90, size=12, replace=False)
    for v in victims:
        do(node, "DELETE", f"/cx/_doc/{int(v)}")
    do(node, "POST", "/cx/_refresh")
    queries = []
    for _ in range(6):
        w = [str(x) for x in rng.choice(VOCAB, rng.integers(1, 4))]
        queries.append({"query": {"match": {"title": " ".join(w)}},
                       "size": 30})
        queries.append({"query": {"bool": {
            "should": [{"match": {"title": t}} for t in w],
            "minimum_should_match": 1,
            "filter": [{"term": {"tag": str(rng.choice(["x", "y"]))}}],
        }}, "size": 30})
    queries.append({"knn": {
        "field": "vec", "query_vector": rng.standard_normal(DIMS).tolist(),
        "k": 15, "num_candidates": 40}, "_source": False, "size": 15})
    for body in queries:
        r_mesh, r_loop, engaged = mesh_vs_loop(node, "cx", body,
                                               monkeypatch)
        assert engaged == 1, body
        assert hits_of(r_mesh) == hits_of(r_loop), body
        assert r_mesh["hits"]["total"] == r_loop["hits"]["total"], body
        # no deleted doc resurfaces through the mesh live mask
        for h in r_mesh["hits"]["hits"]:
            assert int(h["_id"]) not in set(int(v) for v in victims), body


def test_per_shard_idf_semantics(node, monkeypatch):
    """Mesh scoring uses each shard's OWN statistics (ES default), so
    the mesh equals the default loop exactly — while dfs_query_then_fetch
    (global stats) takes the loop with a typed fallback and produces the
    layout-independent scores the mesh path must not fake."""
    seed(node, "idf8", n_shards=8, n_docs=100, seed=7)
    body = {"query": {"match": {"title": "amber"}}, "size": 40}
    r_mesh, r_loop, engaged = mesh_vs_loop(node, "idf8", body,
                                           monkeypatch)
    assert engaged == 1
    assert hits_of(r_mesh) == hits_of(r_loop)
    svc = node.search_service
    fb = svc.mesh_executor.counters.get("fallback.dfs_stats", 0)
    before = svc.mesh_executor.mesh_searches
    r_dfs = do(node, "POST", "/idf8/_search",
               params={"search_type": "dfs_query_then_fetch"},
               body=dict(body))
    assert svc.mesh_executor.mesh_searches == before, \
        "dfs search must not ride the mesh (per-shard stats binding)"
    assert svc.mesh_executor.counters["fallback.dfs_stats"] == fb + 1
    assert r_dfs["hits"]["hits"], "dfs loop fallback must still answer"


# -------------------------------------------------------------- fallback


def test_fallback_one_device(node, monkeypatch):
    """With a single visible device the mesh declines (typed counter)
    and the loop answers — no error, same results."""
    seed(node, "one8", n_shards=4, n_docs=50, seed=3)
    svc = node.search_service
    monkeypatch.setattr(type(svc.mesh_executor), "available_devices",
                        staticmethod(lambda: 1))
    fb = svc.mesh_executor.counters.get("fallback.not_enough_devices", 0)
    before = svc.mesh_executor.mesh_searches
    r = do(node, "POST", "/one8/_search",
           body={"query": {"match": {"title": "amber"}}, "size": 20})
    assert svc.mesh_executor.mesh_searches == before
    assert svc.mesh_executor.counters["fallback.not_enough_devices"] \
        == fb + 1
    assert r["hits"]["total"]["value"] > 0


def test_fallback_disabled_env(node, monkeypatch):
    seed(node, "off8", n_shards=4, n_docs=40, seed=3)
    svc = node.search_service
    monkeypatch.setenv("ESTPU_MESH_SERVING", "0")
    fb = svc.mesh_executor.counters.get("fallback.disabled", 0)
    r = do(node, "POST", "/off8/_search",
           body={"query": {"match": {"title": "amber"}}, "size": 20})
    assert svc.mesh_executor.counters["fallback.disabled"] == fb + 1
    assert r["hits"]["total"]["value"] > 0


def test_fallback_knn_over_packed_ceiling(node, monkeypatch):
    """kNN over a corpus whose global-id space exceeds the float-pack
    ceiling declines cleanly (the bm25 analogue is pinned in
    test_mesh_executor) — loop still answers, counter ticks."""
    import elasticsearch_tpu.ops.plan as plan_mod
    rng = seed(node, "kovf", n_shards=4, n_docs=60, seed=3)
    monkeypatch.setattr(plan_mod, "PACKED_ID_LIMIT", 1)
    monkeypatch.setattr(plan_mod, "check_packed_id_limit",
                        lambda nd, where: None)
    svc = node.search_service
    fb = svc.mesh_executor.counters.get("fallback.packed_id_ceiling", 0)
    before = svc.mesh_executor.mesh_searches
    r = do(node, "POST", "/kovf/_search", body={
        "knn": {"field": "vec",
                "query_vector": rng.standard_normal(DIMS).tolist(),
                "k": 10, "num_candidates": 32},
        "_source": False, "size": 10})
    assert svc.mesh_executor.mesh_searches == before
    assert svc.mesh_executor.counters["fallback.packed_id_ceiling"] \
        == fb + 1
    assert r["hits"]["hits"]


def test_fallback_knn_with_filter(node, monkeypatch):
    """Filtered kNN is not mesh-resident yet — typed fallback, loop
    answers with the filter applied."""
    rng = seed(node, "kf", n_shards=4, n_docs=60, seed=3)
    svc = node.search_service
    fb = svc.mesh_executor.counters.get("fallback.knn_filter", 0)
    before = svc.mesh_executor.mesh_searches
    r = do(node, "POST", "/kf/_search", body={
        "knn": {"field": "vec",
                "query_vector": rng.standard_normal(DIMS).tolist(),
                "k": 10, "num_candidates": 32,
                "filter": {"term": {"tag": "x"}}},
        "_source": False, "size": 10})
    assert svc.mesh_executor.mesh_searches == before
    assert svc.mesh_executor.counters["fallback.knn_filter"] == fb + 1
    assert r["hits"]["hits"]


# ------------------------------------------------- replica-axis cohorts


def test_replica_cohort_byte_identical(node):
    """A continuous-batching cohort launched replica-sharded over the
    mesh (corpus replicated, Q axis split) returns byte-identical
    packed rows to the single-device launch, and counts dispatches."""
    from elasticsearch_tpu.search.batching import PlanBatcher, _Entry
    from elasticsearch_tpu.search.plan import bind_plan, compile_plan
    from elasticsearch_tpu.search.queries import parse_query
    seed(node, "rb", n_shards=1, n_docs=200, seed=3)
    searcher = node.indices_service.get("rb").shard_searchers()[0]
    ctx = searcher._contexts()[0]
    q = parse_query({"match": {"title": "amber dune"}}).rewrite(searcher)
    bp = bind_plan(compile_plan(q, searcher), ctx)
    k, k1, b = 10, searcher.k1, searcher.b

    solo = PlanBatcher()
    e1 = [_Entry(bp) for _ in range(16)]
    solo._run(e1, ctx, k, k1, b)
    assert solo.mesh_cohorts == 0

    meshed = PlanBatcher()
    meshed.mesh = node.search_service.mesh_executor
    before = meshed.mesh.counters.get("dispatch.replica", 0)
    e2 = [_Entry(bp) for _ in range(16)]
    meshed._run(e2, ctx, k, k1, b)
    assert meshed.mesh_cohorts == 1
    assert meshed.mesh.counters["dispatch.replica"] == before + 16

    for a, b_ in zip(e1, e2):
        va, ia, ta = a.result
        vb, ib, tb = b_.result
        assert np.array_equal(va, vb) and np.array_equal(ia, ib)
        assert ta == tb
    assert "mesh_cohorts" in meshed.stats()


def test_replica_mesh_sizing(node):
    """replica_mesh_for: largest pow2 ≤ min(cohort, devices); None
    below two devices or for 1-row cohorts."""
    be = node.search_service.mesh_executor
    assert be.replica_mesh_for(1) is None
    assert be.replica_mesh_for(2).devices.size == 2
    assert be.replica_mesh_for(32).devices.size == 8
    assert be.replica_mesh_for(12).devices.size == 8


def test_fastpath_mesh_cohorts(tmp_path, monkeypatch):
    """ESTPU_FASTPATH_MESH=1: the native front's v1 cohorts launch
    replica-sharded over the mesh — responses match the Python path
    (the native-front parity contract) and the dispatch counters tick."""
    import json
    import urllib.request

    from elasticsearch_tpu.rest import native_http
    if not native_http.available():
        pytest.skip("native http front unavailable")
    monkeypatch.setenv("ESTPU_FASTPATH_MESH", "1")
    n = Node(settings=Settings.from_dict({
        "http": {"native": {"fast_nb_buckets": "64,128",
                            "fast_kernel": "v1",
                            "fast_max_k": 200}},
    }), data_path=str(tmp_path / "data"))
    try:
        port = n.start(0)
        if not isinstance(n._http, native_http.NativeHttpFront):
            pytest.skip("native front slot unavailable")
        rng = np.random.default_rng(42)
        lines = []
        for i in range(200):
            lines.append(json.dumps({"index": {"_index": "books",
                                               "_id": str(i)}}))
            lines.append(json.dumps({"title": " ".join(
                rng.choice(VOCAB, rng.integers(3, 10)))}))
        data = ("\n".join(lines) + "\n").encode()
        r = urllib.request.Request(
            f"http://127.0.0.1:{port}/_bulk", data=data, method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        urllib.request.urlopen(r).read()
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/books/_refresh",
            method="POST")).read()
        fp = n._http.fastpath
        fp.refresh_registration()
        assert fp._reg is not None
        assert fp.mesh_backend is n.search_service.mesh_executor
        assert fp._reg["rmesh"] is not None, \
            "registration must bind a replica mesh"
        body = {"query": {"match": {"title": "amber dune"}},
                "size": 20, "_source": False}
        before = n.search_service.mesh_executor.counters.get(
            "dispatch.replica", 0)
        rq = urllib.request.Request(
            f"http://127.0.0.1:{port}/books/_search",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        fast = json.loads(urllib.request.urlopen(rq).read())
        assert fp.stats.get("mesh_cohorts", 0) >= 1, fp.stats
        assert n.search_service.mesh_executor.counters[
            "dispatch.replica"] > before
        status, slow = n.rest_controller.dispatch(
            "POST", "/books/_search", None, dict(body))
        assert status == 200
        assert fast["hits"]["total"] == slow["hits"]["total"]
        fh = [(h["_id"], h["_score"]) for h in fast["hits"]["hits"]]
        sh = [(h["_id"], h["_score"]) for h in slow["hits"]["hits"]]
        assert len(fh) == len(sh)
        for (fi, fs), (si, ss) in zip(fh, sh):
            # the native-front parity contract (test_native_http):
            # float32 noise between tree-order and dense summation
            assert fs == pytest.approx(ss, rel=2e-3), (fi, si)
    finally:
        n.close()


# ------------------------------------------------------------ telemetry


def test_kernels_mesh_surface(node, monkeypatch):
    """GET /_kernels gains a `mesh` section: dispatch/fallback counters
    and per-DEVICE HBM residency of the cached mesh corpora — 8 chips,
    each holding its own shard's slabs."""
    rng = seed(node, "tele8", n_shards=8, n_docs=80, seed=3)
    for body in pinned_mix(rng)[:1] + pinned_mix(rng)[-1:]:
        do(node, "POST", "/tele8/_search", body=dict(body))
    r = do(node, "GET", "/_kernels")
    mesh = r["mesh"]
    assert mesh["devices"] == 8
    assert mesh["counters"].get("dispatch.shard", 0) >= 1
    assert mesh["counters"].get("dispatch.knn", 0) >= 1
    res = mesh["residency"]
    assert len(res) == 8, f"expected 8 devices resident, got {res.keys()}"
    for dev, classes in res.items():
        assert classes.get("postings", 0) > 0, (dev, classes)
        assert classes.get("vectors", 0) > 0, (dev, classes)
    # node metrics mirror: search.mesh.dispatch{axis} counted
    stats = do(node, "GET", "/_nodes/stats")
    metrics = next(iter(stats["nodes"].values()))["telemetry"]["metrics"]
    rows = metrics.get("search.mesh.dispatch", [])
    assert any(row["labels"].get("axis") == "shard" and row["value"] >= 1
               for row in rows), rows


def test_mesh_profile_attribution(node):
    """`profile: true` rides the mesh: the response carries a
    `[index][_mesh]` profile entry whose device record attributes the
    launch per chip (mesh_shape + device list), and the mesh still
    serves the query (the gate no longer bounces profiled searches)."""
    seed(node, "prof8", n_shards=8, n_docs=80, seed=3)
    svc = node.search_service
    before = svc.mesh_executor.mesh_searches
    r = do(node, "POST", "/prof8/_search", body={
        "query": {"match": {"title": "amber dune"}},
        "size": 10, "profile": True})
    assert svc.mesh_executor.mesh_searches == before + 1
    shards = r["profile"]["shards"]
    mesh_entries = [s for s in shards if s["id"].endswith("[_mesh]")]
    assert len(mesh_entries) == 1, [s["id"] for s in shards]
    launches = mesh_entries[0]["device"]["launches"]
    assert launches[0]["kernel"] == "plan_topk_mesh"
    assert launches[0]["mesh_shape"] == {"shard": 8}
    assert len(launches[0]["device"]) == 8
    assert launches[0]["readback_bytes"] > 0
    # the pinned per-shard invariant holds for the mesh entry too
    q0 = mesh_entries[0]["searches"][0]["query"][0]
    bd = q0["breakdown"]
    assert bd["device_time_in_nanos"] + bd["host_time_in_nanos"] \
        == q0["time_in_nanos"]
