"""SQL plugin tests (model: x-pack/plugin/sql test discipline — parser
round-trips, translation to the query DSL, and end-to-end execution)."""

import pytest

from elasticsearch_tpu.node import Node

MAPPINGS = {
    "properties": {
        "emp_no": {"type": "long"},
        "name": {"type": "keyword"},
        "bio": {"type": "text"},
        "salary": {"type": "double"},
        "dept": {"type": "keyword"},
        "hired": {"type": "date"},
    }
}

DOCS = [
    {"emp_no": 1, "name": "alice", "bio": "staff engineer tpu kernels",
     "salary": 180.0, "dept": "eng", "hired": "2019-03-01"},
    {"emp_no": 2, "name": "bob", "bio": "search infra engineer",
     "salary": 150.0, "dept": "eng", "hired": "2020-07-15"},
    {"emp_no": 3, "name": "carol", "bio": "sales lead",
     "salary": 120.0, "dept": "sales", "hired": "2020-01-10"},
    {"emp_no": 4, "name": "dave", "bio": "sales associate",
     "salary": 90.0, "dept": "sales", "hired": "2021-05-20"},
    {"emp_no": 5, "name": "erin", "bio": "hr generalist",
     "salary": 100.0, "dept": "hr", "hired": "2021-02-01"},
]


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sql")
    n = Node(data_path=str(tmp / "data"))
    idx = n.indices_service.create_index(
        "emp", {"index.number_of_shards": 2}, MAPPINGS)
    for i, d in enumerate(DOCS):
        idx.index_doc(str(i), d)
    idx.refresh()
    yield n
    n.close()


def q(node, sql, **body):
    status, r = node.rest_controller.dispatch(
        "POST", "/_sql", {}, {"query": sql, **body})
    assert status == 200, r
    return r


def test_select_where_order(node):
    r = q(node, "SELECT name, salary FROM emp "
                "WHERE salary >= 100 ORDER BY salary DESC")
    assert [c["name"] for c in r["columns"]] == ["name", "salary"]
    assert [row[0] for row in r["rows"]] == ["alice", "bob", "carol", "erin"]


def test_select_star_and_limit(node):
    r = q(node, "SELECT * FROM emp ORDER BY emp_no ASC LIMIT 2")
    names = [c["name"] for c in r["columns"]]
    assert names == ["bio", "dept", "emp_no", "hired", "name", "salary"]
    assert len(r["rows"]) == 2
    assert r["rows"][0][names.index("name")] == "alice"


def test_scalar_projection(node):
    r = q(node, "SELECT UPPER(name) AS n, salary * 2 AS s2 FROM emp "
                "WHERE name = 'alice'")
    assert r["rows"] == [["ALICE", 360.0]]


def test_full_text_match(node):
    r = q(node, "SELECT name FROM emp WHERE MATCH(bio, 'engineer') "
                "ORDER BY name ASC")
    assert [row[0] for row in r["rows"]] == ["alice", "bob"]


def test_like_and_in_and_between(node):
    r = q(node, "SELECT name FROM emp WHERE name LIKE 'a%'")
    assert [row[0] for row in r["rows"]] == ["alice"]
    r = q(node, "SELECT name FROM emp WHERE dept IN ('hr', 'sales') "
                "ORDER BY name ASC")
    assert [row[0] for row in r["rows"]] == ["carol", "dave", "erin"]
    r = q(node, "SELECT name FROM emp WHERE salary BETWEEN 95 AND 125 "
                "ORDER BY salary ASC")
    assert [row[0] for row in r["rows"]] == ["erin", "carol"]


def test_group_by_aggregates(node):
    r = q(node, "SELECT dept, COUNT(*) AS c, AVG(salary) AS avg_sal, "
                "MAX(salary) AS mx FROM emp GROUP BY dept "
                "ORDER BY dept ASC")
    assert r["rows"] == [
        ["eng", 2, 165.0, 180.0],
        ["hr", 1, 100.0, 100.0],
        ["sales", 2, 105.0, 120.0],
    ]


def test_group_by_having(node):
    r = q(node, "SELECT dept, COUNT(*) AS c FROM emp GROUP BY dept "
                "HAVING COUNT(*) > 1 ORDER BY dept ASC")
    assert r["rows"] == [["eng", 2], ["sales", 2]]


def test_group_by_year(node):
    r = q(node, "SELECT YEAR(hired) AS y, COUNT(*) AS c FROM emp "
                "GROUP BY YEAR(hired) ORDER BY y ASC")
    assert r["rows"] == [[2019, 1], [2020, 2], [2021, 2]]


def test_global_aggregates_no_group(node):
    r = q(node, "SELECT COUNT(*), SUM(salary), MIN(salary) FROM emp")
    assert r["rows"] == [[5, 640.0, 90.0]]


def test_count_distinct(node):
    r = q(node, "SELECT COUNT(DISTINCT dept) FROM emp")
    assert r["rows"] == [[3]]


def test_show_tables_and_describe(node):
    r = q(node, "SHOW TABLES")
    assert ["emp", "TABLE", "INDEX"] in r["rows"]
    r = q(node, "DESCRIBE emp")
    cols = {row[0]: row[1] for row in r["rows"]}
    assert cols["salary"] == "double"
    assert cols["hired"] == "datetime"
    assert cols["bio"] == "text"


def test_constant_select(node):
    r = q(node, "SELECT 1 + 1")
    assert r["rows"] == [[2]]


def test_cursor_paging(node):
    r = q(node, "SELECT name FROM emp ORDER BY emp_no ASC", fetch_size=2)
    assert len(r["rows"]) == 2
    assert "cursor" in r
    status, r2 = node.rest_controller.dispatch(
        "POST", "/_sql", {}, {"cursor": r["cursor"]})
    assert status == 200
    assert len(r2["rows"]) == 2
    status, r3 = node.rest_controller.dispatch(
        "POST", "/_sql", {}, {"cursor": r2["cursor"]})
    assert r3["rows"] == [["erin"]]
    assert "cursor" not in r3


def test_sql_translate(node):
    status, r = node.rest_controller.dispatch(
        "POST", "/_sql/translate", {},
        {"query": "SELECT name FROM emp WHERE salary > 100 "
                  "ORDER BY salary DESC"})
    assert status == 200
    assert r["query"] == {"range": {"salary": {"gt": 100}}}
    assert r["sort"] == [{"salary": {"order": "desc"}}]


def test_sql_close_cursor(node):
    r = q(node, "SELECT name FROM emp", fetch_size=1)
    status, res = node.rest_controller.dispatch(
        "POST", "/_sql/close", {}, {"cursor": r["cursor"]})
    assert res["succeeded"] is True
    status, res = node.rest_controller.dispatch(
        "POST", "/_sql/close", {}, {"cursor": r["cursor"]})
    assert res["succeeded"] is False


def test_txt_format(node):
    status, r = node.rest_controller.dispatch(
        "POST", "/_sql", {"format": "txt"},
        {"query": "SELECT name FROM emp WHERE dept = 'hr'"})
    assert "name" in r["_cat"] and "erin" in r["_cat"]


def test_csv_format(node):
    status, r = node.rest_controller.dispatch(
        "POST", "/_sql", {"format": "csv"},
        {"query": "SELECT name, salary FROM emp WHERE dept = 'hr'"})
    assert r["_cat"].splitlines() == ["name,salary", "erin,100.0"]


def test_is_null_and_not(node):
    r = q(node, "SELECT name FROM emp WHERE NOT dept = 'eng' "
                "AND salary IS NOT NULL ORDER BY name ASC")
    assert [row[0] for row in r["rows"]] == ["carol", "dave", "erin"]


def test_distinct_rows(node):
    r = q(node, "SELECT DISTINCT dept FROM emp ORDER BY dept ASC")
    assert [row[0] for row in r["rows"]] == ["eng", "hr", "sales"]


def test_show_functions(node):
    r = q(node, "SHOW FUNCTIONS LIKE 'CO%'")
    names = [row[0] for row in r["rows"]]
    assert "COUNT" in names and "CONCAT" in names


def test_group_order_by_exceeding_fetch_size(node):
    # ORDER BY must see ALL groups even when they exceed one composite page
    r = q(node, "SELECT dept, MAX(salary) AS m FROM emp GROUP BY dept "
                "ORDER BY m DESC LIMIT 2", fetch_size=1)
    # paged: first page has 1 row (fetch_size=1) but ordering is global
    assert r["rows"] == [["eng", 180.0]]
    status, r2 = node.rest_controller.dispatch(
        "POST", "/_sql", {}, {"cursor": r["cursor"]})
    assert r2["rows"] == [["sales", 120.0]]


def test_group_having_filters_across_pages(node):
    # HAVING filtering an entire page must not kill the cursor
    r = q(node, "SELECT dept, MAX(salary) AS m FROM emp GROUP BY dept "
                "HAVING MAX(salary) >= 120", fetch_size=1)
    collected = list(r["rows"])
    while "cursor" in r:
        status, r = node.rest_controller.dispatch(
            "POST", "/_sql", {}, {"cursor": r["cursor"]})
        collected += r["rows"]
    assert sorted(collected) == [["eng", 180.0], ["sales", 120.0]]


def test_txt_format_carries_cursor(node):
    status, r = node.rest_controller.dispatch(
        "POST", "/_sql", {"format": "txt"},
        {"query": "SELECT name FROM emp ORDER BY emp_no ASC",
         "fetch_size": 2})
    assert "_headers" in r and r["_headers"]["Cursor"]
    status, r2 = node.rest_controller.dispatch(
        "POST", "/_sql", {"format": "txt"},
        {"cursor": r["_headers"]["Cursor"]})
    # continuation page: rows only, no header line
    assert "name" not in r2["_cat"]
    assert "carol" in r2["_cat"] or "dave" in r2["_cat"]


def test_distinct_with_limit(node):
    # dedup happens BEFORE the limit — 3 distinct depts exist
    r = q(node, "SELECT DISTINCT dept FROM emp LIMIT 3")
    assert sorted(row[0] for row in r["rows"]) == ["eng", "hr", "sales"]


def test_grouped_order_desc_nulls_last(node):
    idx = node.indices_service.get("emp")
    idx.index_doc("no-dept", {"emp_no": 9, "name": "zoe", "salary": 70.0})
    idx.refresh()
    r = q(node, "SELECT dept, COUNT(*) AS c FROM emp GROUP BY dept "
                "ORDER BY dept DESC")
    keys = [row[0] for row in r["rows"]]
    assert keys == ["sales", "hr", "eng", None]
