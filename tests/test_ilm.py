"""ILM tests: policy CRUD, the phase/step state machine, rollover/shrink/
freeze/delete actions, explain, failure parking + retry (model: the
reference's IndexLifecycleRunnerTests and TimeseriesLifecycleTypeTests,
driven with an injected clock like its DeterministicTaskQueue tests)."""

import tempfile

import pytest

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.xpack.ilm import parse_time_ms

DAY = 86400.0
T0 = 1_700_000_000.0


@pytest.fixture()
def node():
    n = Node(data_path=tempfile.mkdtemp())
    yield n
    n.close()


def make_managed_index(node, name="logs-000001", alias="logs",
                       policy="logs-policy", extra=None):
    settings = {"index.lifecycle.name": policy,
                "index.lifecycle.rollover_alias": alias,
                "index.creation_date": int(T0 * 1000)}
    settings.update(extra or {})
    idx = node.indices_service.create_index(name, settings)
    node.metadata_service.update_aliases(
        [{"add": {"index": name, "alias": alias, "is_write_index": True}}])
    return idx


def test_parse_time_ms():
    assert parse_time_ms("30d") == 30 * 86400_000
    assert parse_time_ms("0ms") == 0
    assert parse_time_ms("90s") == 90_000
    with pytest.raises(IllegalArgumentException):
        parse_time_ms("5 fortnights")


def test_policy_crud(node):
    ilm = node.ilm_service
    ilm.put_policy("p", {"policy": {"phases": {
        "hot": {"actions": {"rollover": {"max_docs": 3}}},
        "delete": {"min_age": "30d", "actions": {"delete": {}}}}}})
    got = ilm.get_policy("p")
    assert got["p"]["version"] == 1
    assert "hot" in got["p"]["policy"]["phases"]
    ilm.put_policy("p", {"policy": {"phases": {
        "hot": {"actions": {"set_priority": {"priority": 100}}}}}})
    assert ilm.get_policy("p")["p"]["version"] == 2
    ilm.delete_policy("p")
    with pytest.raises(ResourceNotFoundException):
        ilm.get_policy("p")


def test_policy_validation(node):
    ilm = node.ilm_service
    with pytest.raises(IllegalArgumentException):
        ilm.put_policy("bad", {"policy": {"phases": {
            "tropical": {"actions": {}}}}})
    with pytest.raises(IllegalArgumentException):
        ilm.put_policy("bad", {"policy": {"phases": {
            "hot": {"actions": {"delete": {}}}}}})  # delete not valid in hot


def test_delete_policy_in_use_rejected(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "hot": {"actions": {"set_priority": {"priority": 10}}}}}})
    make_managed_index(node)
    with pytest.raises(IllegalArgumentException):
        ilm.delete_policy("logs-policy")


def test_hot_rollover_on_max_docs(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "hot": {"actions": {"rollover": {"max_docs": 3}}}}}})
    idx = make_managed_index(node)
    for i in range(2):
        idx.index_doc(str(i), {"n": i})
    idx.refresh()
    ilm.tick(now=T0 + 60)
    # conditions not met yet
    assert node.metadata_service.write_target("logs") == "logs-000001"
    idx.index_doc("2", {"n": 2})
    idx.refresh()
    ilm.tick(now=T0 + 120)
    assert node.indices_service.has("logs-000002")
    assert node.metadata_service.write_target("logs") == "logs-000002"
    # original index recorded indexing_complete
    assert idx.settings.get("index.lifecycle.indexing_complete") is True


def test_warm_phase_readonly_and_forcemerge_after_min_age(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "warm": {"min_age": "1d",
                 "actions": {"readonly": {}, "forcemerge":
                             {"max_num_segments": 1}}}}}})
    idx = make_managed_index(node)
    for i in range(4):
        idx.index_doc(str(i), {"n": i})
        idx.refresh()  # several segments
    ilm.tick(now=T0 + 3600)           # too young
    assert idx.settings.get("index.blocks.write") is None
    ilm.tick(now=T0 + 2 * DAY)
    assert idx.settings.get("index.blocks.write") is True
    assert all(len(sh.segments) <= 1 for sh in idx.shards)
    st = ilm.explain("logs-000001", now=T0 + 2 * DAY)
    assert st["phase"] == "warm"


def test_delete_phase_removes_index(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "delete": {"min_age": "7d", "actions": {"delete": {}}}}}})
    make_managed_index(node)
    ilm.tick(now=T0 + DAY)
    assert node.indices_service.has("logs-000001")
    ilm.tick(now=T0 + 8 * DAY)
    assert not node.indices_service.has("logs-000001")


def test_cold_freeze(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "cold": {"min_age": "10d", "actions": {"freeze": {}}}}}})
    idx = make_managed_index(node)
    ilm.tick(now=T0 + 11 * DAY)
    assert idx.settings.get("index.frozen") is True


def test_shrink_action(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "warm": {"min_age": "1d",
                 "actions": {"shrink": {"number_of_shards": 1}}}}}})
    idx = make_managed_index(node, extra={"index.number_of_shards": 2})
    for i in range(6):
        idx.index_doc(str(i), {"n": i})
    idx.refresh()
    ilm.tick(now=T0 + 2 * DAY)
    assert not node.indices_service.has("logs-000001")
    shrunk = node.indices_service.get("shrink-logs-000001")
    assert shrunk.num_shards == 1
    from elasticsearch_tpu.search.queries import parse_query
    total = sum(r.total_hits for r in (
        s.query_phase(parse_query({"match_all": {}}), size=10)
        for s in shrunk.shard_searchers()))
    assert total == 6


def test_phase_progression_hot_to_delete(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "hot": {"actions": {"set_priority": {"priority": 100}}},
        "warm": {"min_age": "1d", "actions": {"readonly": {}}},
        "delete": {"min_age": "3d", "actions": {"delete": {}}}}}})
    idx = make_managed_index(node)
    ilm.tick(now=T0 + 1)
    assert idx.settings.get("index.priority") == 100
    assert ilm.explain("logs-000001", now=T0 + 1)["phase"] == "hot"
    ilm.tick(now=T0 + 1.5 * DAY)
    assert idx.settings.get("index.blocks.write") is True
    ilm.tick(now=T0 + 4 * DAY)
    assert not node.indices_service.has("logs-000001")


def test_failed_step_parks_and_retry(node):
    ilm = node.ilm_service
    # rollover without a rollover_alias setting → failure is recorded
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "hot": {"actions": {"rollover": {"max_docs": 1}}}}}})
    idx = node.indices_service.create_index(
        "lonely-000001", {"index.lifecycle.name": "logs-policy",
                          "index.creation_date": int(T0 * 1000)})
    ilm.tick(now=T0 + 60)
    ex = ilm.explain("lonely-000001", now=T0 + 60)
    assert "failed_step" in ex
    # a later tick does not re-run the failed step
    ilm.tick(now=T0 + 120)
    # retry clears the failure; provide the alias so it can succeed
    node.metadata_service.update_aliases(
        [{"add": {"index": "lonely-000001", "alias": "lonely",
                  "is_write_index": True}}])
    idx.update_settings({"index.lifecycle.rollover_alias": "lonely"})
    idx.index_doc("0", {})
    idx.refresh()
    ilm.retry("lonely-000001")
    ilm.tick(now=T0 + 180)
    assert node.indices_service.has("lonely-000002")


def test_stop_halts_progression(node):
    ilm = node.ilm_service
    ilm.put_policy("logs-policy", {"policy": {"phases": {
        "delete": {"min_age": "1d", "actions": {"delete": {}}}}}})
    make_managed_index(node)
    ilm.stop()
    ilm.tick(now=T0 + 5 * DAY)
    assert node.indices_service.has("logs-000001")
    assert ilm.status() == "STOPPED"
    ilm.start()
    ilm.tick(now=T0 + 5 * DAY)
    assert not node.indices_service.has("logs-000001")


def test_rest_api(node):
    c = node.rest_controller
    s, r = c.dispatch("PUT", "/_ilm/policy/p1", None, {"policy": {"phases": {
        "hot": {"actions": {"set_priority": {"priority": 50}}}}}})
    assert s == 200 and r["acknowledged"]
    s, r = c.dispatch("GET", "/_ilm/policy/p1", None, None)
    assert s == 200 and "p1" in r
    s, r = c.dispatch("GET", "/_ilm/status", None, None)
    assert r["operation_mode"] == "RUNNING"
    s, r = c.dispatch("PUT", "/idx1", None,
                      {"settings": {"index.lifecycle.name": "p1"}})
    assert s == 200, r
    node.ilm_service.tick()
    s, r = c.dispatch("GET", "/idx1/_ilm/explain", None, None)
    assert s == 200 and r["indices"]["idx1"]["managed"] is True
    assert r["indices"]["idx1"]["policy"] == "p1"
    s, r = c.dispatch("POST", "/idx1/_ilm/remove", None, None)
    assert s == 200 and r["removed"] == ["idx1"]
    s, r = c.dispatch("GET", "/idx1/_ilm/explain", None, None)
    assert r["indices"]["idx1"]["managed"] is False
    s, r = c.dispatch("DELETE", "/_ilm/policy/p1", None, None)
    assert s == 200


def test_put_settings_rest(node):
    c = node.rest_controller
    c.dispatch("PUT", "/idx2", None, None)
    s, r = c.dispatch("PUT", "/idx2/_settings", None,
                      {"index": {"priority": 7}})
    assert s == 200
    assert node.indices_service.get("idx2").settings.get("index.priority") == 7
    s, r = c.dispatch("PUT", "/idx2/_settings", None,
                      {"index.number_of_shards": 5})
    assert s == 400


def test_searchable_snapshot_action_mounts_lazily(tmp_path):
    """The cold-phase searchable_snapshot action snapshots, drops the
    local copy, and remounts LAZILY (ref: ILM SearchableSnapshotAction
    snapshot→mount→swap steps)."""
    import glob
    import os
    import time as _time
    from elasticsearch_tpu.node import Node

    node = Node(data_path=str(tmp_path / "ilmss"))

    def call(method, path, body=None, expect=200, **params):
        st, r = node.rest_controller.dispatch(method, path, params, body)
        assert st == expect, r
        return r

    try:
        call("PUT", "/_snapshot/coldrepo", {
            "type": "fs", "settings": {"location": str(tmp_path / "cr")}})
        call("PUT", "/_ilm/policy/tier", {"policy": {"phases": {
            "cold": {"min_age": "0ms", "actions": {
                "searchable_snapshot": {
                    "snapshot_repository": "coldrepo"}}}}}})
        call("PUT", "/olddata", {
            "settings": {"index.lifecycle.name": "tier"},
            "mappings": {"properties": {"t": {"type": "text"}}}})
        for i in range(10):
            call("PUT", f"/olddata/_doc/{i}", {"t": f"archived {i}"},
                 expect=201)
        call("POST", "/olddata/_refresh")

        node.ilm_service.tick(now=_time.time() + 10)

        idx = node.indices_service.get("olddata")
        assert str(idx.settings.get("index.store.type")) == "snapshot"
        shard_dir = os.path.join(node.data_path, "olddata", "0")
        assert os.path.exists(os.path.join(shard_dir,
                                           "snapshot_store.json"))
        # data files dropped at mount; the first search streams them in
        assert glob.glob(os.path.join(shard_dir, "*", "arrays.npz")) == []
        r = call("POST", "/olddata/_search",
                 {"query": {"match": {"t": "archived"}}, "size": 20})
        assert r["hits"]["total"]["value"] == 10
        assert glob.glob(os.path.join(shard_dir, "*", "arrays.npz")) != []
        st, _ = node.rest_controller.dispatch(
            "PUT", "/olddata/_doc/99", None, {"t": "nope"})
        assert st >= 400   # mounted = read-only
    finally:
        node.close()
