"""Action seam + typed client + thread pools (ref: ActionModule /
NodeClient tests, RestClient round-robin/sniffer tests,
ThreadPool/EsRejectedExecutionException tests)."""

import threading
import time

import pytest

from elasticsearch_tpu.common.threadpool import (
    EsRejectedExecutionException,
    TaskTrackingPool,
    ThreadPool,
)
from elasticsearch_tpu.node import Node


# --------------------------------------------------------------- threadpool

def test_pool_executes_and_tracks_ewma():
    pool = TaskTrackingPool("t", 2, 10)
    try:
        f = pool.submit(lambda: sum(range(1000)))
        assert f.result(5) == 499500
        for _ in range(5):
            pool.submit(time.sleep, 0.01).result(5)
        st = pool.stats()
        assert st["completed"] >= 6
        assert st["ewma_task_ms"] > 0
    finally:
        pool.shutdown()


def test_pool_rejects_when_full():
    pool = TaskTrackingPool("tiny", 1, 1)
    try:
        gate = threading.Event()
        pool.execute(gate.wait)          # occupies the worker
        deadline = time.time() + 5
        while pool.stats()["active"] < 1 and time.time() < deadline:
            time.sleep(0.01)             # wait until the worker holds it
        pool.execute(lambda: None)       # fills the queue
        with pytest.raises(EsRejectedExecutionException):
            for _ in range(5):
                pool.execute(lambda: None)
        gate.set()
        assert pool.stats()["rejected"] >= 1
    finally:
        gate.set()
        pool.shutdown()


def test_threadpool_registry_names():
    tp = ThreadPool(processors=4)
    try:
        assert set(tp.stats()) == {"search", "search_throttled", "write",
                                   "get", "management", "snapshot"}
        assert tp.executor("search").size == 7   # 3*p/2+1
    finally:
        tp.shutdown()


# -------------------------------------------------------------- action seam

def test_node_client_actions(tmp_path):
    node = Node(data_path=str(tmp_path / "n"))
    try:
        from elasticsearch_tpu import action as act
        node.client.execute(act.CREATE_INDEX, "t", None,
                            {"properties": {"x": {"type": "long"}}})
        node.client.execute(act.INDEX, "t", "1", {"x": 5})
        node.client.execute(act.REFRESH, "t")
        r = node.client.execute(act.SEARCH, "t",
                                {"query": {"match_all": {}}})
        assert r["hits"]["total"]["value"] == 1
        got = node.client.execute(act.GET, "t", "1")
        assert got.source == {"x": 5}
        # unknown actions are a clear error
        with pytest.raises(KeyError):
            node.client.execute("indices:data/read/nonexistent")
        # the REST search handler routes through the seam
        assert "indices:data/read/search" in node.client.action_names()
        # async execution forks onto the named pool
        box = {}
        ev = threading.Event()
        node.client.execute_async(
            act.SEARCH, "t", {"query": {"match_all": {}}},
            done=lambda r, e: (box.update(r=r, e=e), ev.set()))
        assert ev.wait(10) and box["e"] is None
        assert box["r"]["hits"]["total"]["value"] == 1
        assert node.threadpool.executor("search").stats()["completed"] >= 1
    finally:
        node.close()


def test_plugin_contributed_action(tmp_path):
    import json as _json
    import os
    import textwrap
    pdir = tmp_path / "plugins" / "actplug"
    os.makedirs(pdir)
    (pdir / "plugin.json").write_text(_json.dumps(
        {"name": "actplug", "module": "act_plugin", "class": "ESPlugin"}))
    (pdir / "act_plugin.py").write_text(textwrap.dedent("""
        from elasticsearch_tpu.plugins import Plugin
        class ESPlugin(Plugin):
            name = "actplug"
            def actions(self):
                return {"cluster:custom/echo":
                        lambda node: (lambda msg: {"echo": msg})}
    """))
    from elasticsearch_tpu.common.settings import Settings
    node = Node(settings=Settings.from_dict(
        {"path": {"plugins": str(tmp_path / "plugins")}}),
        data_path=str(tmp_path / "d"))
    try:
        assert node.client.execute("cluster:custom/echo", "hi") == \
            {"echo": "hi"}
    finally:
        node.close()


# -------------------------------------------------------------- typed client

def test_typed_client_roundtrip(tmp_path):
    from elasticsearch_tpu.client import Elasticsearch, TransportError

    node = Node(data_path=str(tmp_path / "n"))
    port = node.start(0)
    try:
        es = Elasticsearch([f"http://127.0.0.1:{port}"])
        assert es.ping()
        es.indices.create("logs", {"mappings": {"properties": {
            "msg": {"type": "text"}, "n": {"type": "long"}}}})
        assert es.indices.exists("logs")
        es.index("logs", {"msg": "hello world", "n": 1}, id="1")
        es.index("logs", {"msg": "goodbye world", "n": 2}, id="2",
                 refresh=True)
        assert es.get("logs", "1")["_source"]["n"] == 1
        assert es.exists("logs", "1") and not es.exists("logs", "404")

        r = es.search("logs", {"query": {"match": {"msg": "world"}}})
        assert r["hits"]["total"]["value"] == 2
        assert es.count("logs")["count"] == 2

        # NDJSON bulk
        r = es.bulk([
            {"index": {"_index": "logs", "_id": "3"}},
            {"msg": "bulked", "n": 3},
            {"delete": {"_index": "logs", "_id": "2"}},
        ], refresh=True)
        assert not r["errors"]
        assert es.count("logs")["count"] == 2

        # msearch through the client (parallel on the search pool)
        r = es.msearch([
            {"index": "logs"}, {"query": {"match_all": {}}},
            {"index": "logs"}, {"query": {"match": {"msg": "bulked"}}},
        ])
        assert [x["hits"]["total"]["value"] for x in r["responses"]] \
            == [2, 1]

        # update + delete + error surface
        es.update("logs", "1", {"doc": {"n": 10}})
        assert es.get("logs", "1")["_source"]["n"] == 10
        es.delete("logs", "1")
        with pytest.raises(TransportError) as ei:
            es.get("logs", "1")
        assert ei.value.status == 404
        assert es.cluster.health()["status"] in ("green", "yellow")
    finally:
        node.close()


def test_client_failover_and_sniff(tmp_path):
    from elasticsearch_tpu.client import Elasticsearch

    node = Node(data_path=str(tmp_path / "n"))
    port = node.start(0)
    try:
        # first host is dead: the client marks it and fails over
        es = Elasticsearch(["http://127.0.0.1:9",
                            f"http://127.0.0.1:{port}"], max_retries=4)
        assert es.ping()
        info = es.info()
        assert "version" in info or "cluster_name" in info
        # sniffer rebuilds the host list from /_nodes
        hosts = es.transport.sniff()
        assert hosts
    finally:
        node.close()
